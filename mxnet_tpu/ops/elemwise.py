"""Elementwise unary / binary / scalar operator families.

Mirrors the reference op surface in src/operator/tensor/elemwise_unary_op*.cc,
elemwise_binary_op*.cc, elemwise_binary_broadcast_op*.cc and
elemwise_binary_scalar_op*.cc (MXNet op names preserved). Each op is one jnp
expression — XLA fuses chains of these into single VPU kernels, which is the
TPU-native replacement for the reference's mshadow Kernel<Op,xpu>::Launch +
operator-tuning machinery (src/operator/operator_tune.cc): no per-op tuning is
needed when the compiler does the fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import jax.scipy.special as jsp
from jax import lax

from .registry import register, register_op

_F32EPS = 1e-20


# ---------------------------------------------------------------------------
# unary math family (ref: elemwise_unary_op_basic.cc / _trig.cc / _logexp.cc)
# ---------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sigmoid": lambda x: jnp.reciprocal(1.0 + jnp.exp(-x)),
    "softsign": lambda x: x / (1.0 + jnp.abs(x)),
    "relu": lambda x: jnp.maximum(x, 0),
    "erf": jsp.erf,
    "erfinv": jsp.erfinv,
    "gamma": lambda x: jnp.exp(jsp.gammaln(x)),
    "gammaln": jsp.gammaln,
    "reciprocal": jnp.reciprocal,
    "negative": jnp.negative,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "identity": lambda x: x,
}

for _name, _fn in _UNARY.items():
    _aliases = ("_copy",) if _name == "identity" else ()
    register_op(_name, (lambda f: lambda data: f(data))(_fn), aliases=_aliases)


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("BlockGrad", aliases=("stop_gradient",))
def block_grad(data):
    return lax.stop_gradient(data)


@register("make_loss", aliases=("MakeLoss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0,
              normalization="null"):
    """Marks an output as a loss (ref: src/operator/make_loss.cc):
    forward is identity; backward seeds ones * grad_scale instead of the
    head gradient (the loss-layer contract)."""
    @jax.custom_vjp
    def f(d):
        return d

    def f_fwd(d):
        return d, d

    def f_bwd(d, g):
        shape = d.shape
        if normalization == "batch":
            scale = grad_scale / shape[0]
            return (jnp.full(shape, scale, g.dtype),)
        if normalization == "valid":
            # divide by the VALID element count (ref: make_loss.cc
            # normalization=valid with valid_thresh)
            n_valid = jnp.maximum(
                jnp.sum((d > valid_thresh).astype(g.dtype)), 1.0)
            return (jnp.full(shape, grad_scale, g.dtype) / n_valid,)
        return (jnp.full(shape, grad_scale, g.dtype),)

    f.defvjp(f_fwd, f_bwd)
    return f(data)


# ---------------------------------------------------------------------------
# binary (same-shape) + broadcast family
# (ref: elemwise_binary_op_basic.cc, elemwise_binary_broadcast_op_*.cc)
# ---------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: (a == b),
    "not_equal": lambda a, b: (a != b),
    "greater": lambda a, b: (a > b),
    "greater_equal": lambda a, b: (a >= b),
    "lesser": lambda a, b: (a < b),
    "lesser_equal": lambda a, b: (a <= b),
    "logical_and": lambda a, b: (a.astype(bool) & b.astype(bool)),
    "logical_or": lambda a, b: (a.astype(bool) | b.astype(bool)),
    "logical_xor": lambda a, b: (a.astype(bool) ^ b.astype(bool)),
}

_BOOLEAN = {"equal", "not_equal", "greater", "greater_equal", "lesser",
            "lesser_equal", "logical_and", "logical_or", "logical_xor"}


def _as_f(name, fn):
    if name in _BOOLEAN:
        return lambda lhs, rhs: fn(lhs, rhs).astype(jnp.result_type(lhs))
    return fn


for _name, _fn in _BINARY.items():
    _f = _as_f(_name, _fn)
    register_op("broadcast_" + _name, (lambda f: lambda lhs, rhs: f(lhs, rhs))(_f))
    if _name in ("add", "sub", "mul", "div", "mod"):
        register_op(
            "elemwise_" + _name,
            (lambda f: lambda lhs, rhs: f(lhs, rhs))(_f),
            aliases=("_" + _name,) if _name != "mod" else (),
        )

register_op("_equal", lambda lhs, rhs: _as_f("equal", _BINARY["equal"])(lhs, rhs))
register_op("_maximum", lambda lhs, rhs: jnp.maximum(lhs, rhs))
register_op("_minimum", lambda lhs, rhs: jnp.minimum(lhs, rhs))
register_op("_power", lambda lhs, rhs: jnp.power(lhs, rhs))
register_op("_hypot", lambda lhs, rhs: jnp.hypot(lhs, rhs))


@register("elemwise_sum", aliases=("add_n", "ElementWiseSum"), num_inputs=None)
def elemwise_sum(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# ---------------------------------------------------------------------------
# scalar family (ref: elemwise_binary_scalar_op_basic.cc etc.)
# scalar attr is static -> folded into the compiled kernel.
# ---------------------------------------------------------------------------
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(jnp.full_like(x, s), x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: (x.astype(bool) & bool(s)).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: (x.astype(bool) | bool(s)).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: (x.astype(bool) ^ bool(s)).astype(x.dtype),
}

for _name, _fn in _SCALAR.items():
    register_op(_name, (lambda f: lambda data, scalar=1.0: f(data, scalar))(_fn))


@register("clip")
def clip(data, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    absx = jnp.abs(data)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * data * data, absx - 0.5 / s2)
