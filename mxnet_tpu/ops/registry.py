"""Operator registry — the nnvm-registry analogue.

The reference registers 232 ops via NNVM_REGISTER_OP with attribute functions
(FInferShape/FCompute/FGradient..., ref: include/mxnet/op_attr_types.h:198-309)
and generates the Python API from registry metadata at import
(ref: python/mxnet/ndarray/register.py:30-60). Here an op is a *pure JAX
function* over jax.Arrays plus static attrs:

  - shape/dtype inference  -> jax.eval_shape on the same function (one source
    of truth instead of separate FInferShape/FInferType),
  - FCompute<cpu>/<gpu>    -> one XLA lowering, jit-cached per (shapes, attrs),
  - FGradient              -> jax.vjp of the same function,
  - codegen                -> ``generate_namespace`` builds mx.nd.* / mx.sym.*
                              functions from this registry.

Ops registered here are therefore device-portable by construction; the MXU/
fusion work happens inside XLA (and Pallas kernels registered the same way).
"""
from __future__ import annotations

import functools
import inspect

import jax

from ..base import MXNetError
from .. import telemetry as _telemetry
from ..telemetry import _current_op as _tm_op

_OPS = {}

# the cached SERIES, not the family: series handles survive registry
# resets, and skipping labels() keeps per-dispatch cost to one lock+add
_dispatch_counter = _telemetry.metrics.lazy_metrics(
    lambda reg: reg.counter(
        "mx_op_dispatches_total",
        "eager op dispatches through the jit-wrapping path").labels())


class OpDef:
    """A registered operator.

    Parameters
    ----------
    name : canonical MXNet-compatible op name (e.g. "FullyConnected").
    fn : pure function (*arrays, **attrs) -> array | tuple(arrays).
    num_inputs : int or None (variadic).
    wrap_jit : whether eager calls go through a cached jax.jit of fn.
    """

    def __init__(self, name, fn, aliases=(), num_inputs=None, wrap_jit=True,
                 num_outputs=1, needs_rng=False, optional_arrays=()):
        self.name = name
        self.fn = fn
        self.aliases = tuple(aliases)
        self.num_inputs = num_inputs
        self.num_outputs = num_outputs
        self.wrap_jit = wrap_jit
        # RNG ops take a fresh jax PRNG key as their first array argument;
        # the nd-layer injects it and the autograd tape records it so replay
        # is deterministic (the counter-based analogue of the reference's
        # per-device Philox states, ref: include/mxnet/random_generator.h).
        self.needs_rng = needs_rng
        sig = inspect.signature(fn)
        # "key" is the injected PRNG slot only for RNG ops; elsewhere it is
        # an ordinary attr (e.g. SyncBatchNorm's barrier key string)
        params = [p for p in sig.parameters.values()
                  if not (needs_rng and p.name == "key")]
        # optional *array* params (default None) vs attrs with None
        # defaults: per-op via register(optional_arrays=...), plus names
        # that are always arrays across the op set
        _arrayish = {"bias", "gamma", "state_cell", "sequence_length",
                     "weight"} | set(optional_arrays)
        self.arg_names = tuple(
            p.name for p in params
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            and (p.default is p.empty
                 or (p.default is None and p.name in _arrayish))
        )
        self.has_varargs = any(
            p.kind == p.VAR_POSITIONAL for p in sig.parameters.values())
        self._kwarg_names = tuple(
            p.name
            for p in params
            if p.kind in (p.KEYWORD_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.default is not p.empty
            and not (p.default is None and p.name in _arrayish)
        )
        self.attr_defaults = {
            p.name: p.default for p in params
            if p.kind in (p.KEYWORD_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.default is not p.empty}
        self._jitted = None
        self._warned_unjitted = False

    def __repr__(self):
        return f"<Op {self.name}>"

    @property
    def jitted(self):
        """Cached jit wrapper; attrs are static so each (shape, attr) combo
        compiles once and replays from the XLA executable cache."""
        if self._jitted is None:
            self._jitted = jax.jit(self.fn, static_argnames=self._kwarg_names)
        return self._jitted

    def __call__(self, *arrays, **attrs):
        """Eager execute on jax.Arrays (dispatch is async on the PJRT stream —
        the reference's threaded engine push, done by the runtime)."""
        if not self.wrap_jit:
            return self.fn(*arrays, **attrs)
        if _telemetry.enabled():
            # compile attribution: jax's monitoring bus reports any XLA
            # build this dispatch triggers; the thread-local names the
            # op it gets charged to (telemetry/__init__) — the cached
            # fast path pays two attr writes and one counter bump only.
            # Save/restore (not clear): an enclosing compile_scope or
            # outer op dispatch must get its attribution back
            prev = getattr(_tm_op, "name", None)
            _tm_op.name = self.name
            try:
                return self._eager_jit(arrays, attrs)
            finally:
                _tm_op.name = prev
                _dispatch_counter().inc()
        return self._eager_jit(arrays, attrs)

    def _eager_jit(self, arrays, attrs):
        try:
            return self.jitted(*arrays, **attrs)
        except (TypeError, ValueError):
            try:  # classify by actually hashing the static attrs —
                hash(tuple(sorted(attrs.items())))  # not by message
                unhashable = False
            except TypeError:
                unhashable = True
            if not unhashable:
                raise  # a genuine op error, not a static-attr problem
            # unhashable attr (e.g. a list or an array passed for a
            # static param) — run un-jitted; jnp internals still hit
            # the C++ fast path. Logged once per op so a hot path
            # silently bypassing the XLA executable cache is visible.
            if not self._warned_unjitted:
                self._warned_unjitted = True
                import logging
                logging.getLogger(__name__).warning(
                    "op %s called with unhashable attrs %s; running "
                    "un-jitted (warned once)", self.name,
                    sorted(attrs))
            return self.fn(*arrays, **attrs)


def register_op(name, fn, aliases=(), num_inputs=None, wrap_jit=True,
                num_outputs=1, needs_rng=False, optional_arrays=()):
    """Register a pure JAX function as a framework op (plain-function form)."""
    op = OpDef(name, fn, aliases=aliases, num_inputs=num_inputs,
               wrap_jit=wrap_jit, num_outputs=num_outputs, needs_rng=needs_rng,
               optional_arrays=optional_arrays)
    for key in (name,) + tuple(aliases):
        if key in _OPS:
            raise MXNetError(f"op {key} registered twice")
        _OPS[key] = op
    return op


def register(name=None, aliases=(), num_inputs=None, wrap_jit=True,
             num_outputs=1, needs_rng=False, optional_arrays=()):
    """Decorator form of :func:`register_op`."""

    def deco(fn):
        register_op(name or fn.__name__, fn, aliases=aliases,
                    num_inputs=num_inputs, wrap_jit=wrap_jit,
                    num_outputs=num_outputs, needs_rng=needs_rng,
                    optional_arrays=optional_arrays)
        return fn

    return deco


def get(name):
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def bind_positional_attrs(op, scalars, attrs, err_cls=TypeError):
    """Bind positional non-array call arguments to the op's declared
    attr names in signature order (the reference's generated API has
    real named signatures, python/mxnet/ndarray/register.py codegen —
    nd.one_hot(idx, 3) and x.clip(0, 1) must work positionally).
    Mutates `attrs`. Python semantics: a name given both positionally
    and by keyword raises. The one vararg special case: MXNet spells
    transpose as x.transpose(*axes), so integer overflow onto a sole
    'axes'/'axis' slot packs into a tuple."""
    import numpy as _np
    names = op._kwarg_names
    ints = all(isinstance(s, (int, _np.integer))
               and not isinstance(s, bool) for s in scalars)
    # 'axes' is semantically a tuple, so integer positionals always pack
    # (x.transpose(2, 0, 1) AND the 1-d x.transpose(0)); 'axis' takes a
    # scalar, so it packs only on overflow
    if ints and len(names) >= 1 and names[0] not in attrs and (
            (names[0] == "axes" and scalars)
            or (names[0] == "axis" and len(scalars) > len(names))):
        scalars = [tuple(int(s) for s in scalars)]
    if len(scalars) > len(names):
        raise err_cls(
            "%s: %d positional parameter(s) but only %d declared: %r"
            % (op.name, len(scalars), len(names), list(names)))
    for n, v in zip(names, scalars):
        if n in attrs:
            raise err_cls(
                "%s got multiple values for parameter %r" % (op.name, n))
        if v is not None:
            attrs[n] = v


def find(name):
    return _OPS.get(name)


def list_ops():
    return sorted(_OPS)


def alias_map():
    return dict(_OPS)


def canonical_ops():
    """{canonical name: OpDef}, aliases collapsed — one entry per OpDef
    (the registry-hygiene walk and parity tools iterate real ops, not
    every spelling)."""
    return {op.name: op for op in _OPS.values()}


def fn_name_map():
    """{implementing python function name: canonical op name}.

    The dispatch layer wraps each op's fn in ``jax.jit``, so every HLO
    instruction an op lowers to carries ``jit(<fn name>)`` in its
    op_name metadata — this map is how the profiling cost ledger turns
    that back into the framework op (e.g. ``convolution`` ->
    ``Convolution``, ``sg_xla_conv`` -> ``_sg_xla_conv``). Ops sharing
    one implementation function collapse onto the canonical name
    registered last; the ledger only needs a stable, recognizable
    attribution key."""
    return {op.fn.__name__: name
            for name, op in canonical_ops().items()}


@functools.lru_cache(maxsize=None)
def infer_output(op_name, in_shapes_dtypes, attrs_items):
    """Shape/dtype inference via abstract evaluation (FInferShape/FInferType
    analogue; ref: src/executor/infer_graph_attr_pass.cc) — no FLOPs run."""
    op = get(op_name)
    attrs = dict(attrs_items)
    specs = [jax.ShapeDtypeStruct(s, d) for s, d in in_shapes_dtypes]
    out = jax.eval_shape(functools.partial(op.fn, **attrs), *specs)
    return out
