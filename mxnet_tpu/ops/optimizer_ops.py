"""Fused optimizer-update ops (ref: src/operator/optimizer_op.cc:32-41,
src/operator/contrib/optimizer_op.cc).

The reference exposes each optimizer's update rule as a standalone op so
user code and `update_on_kvstore` servers can apply updates without a
Python Optimizer object. Here each op is one pure jitted XLA program —
elementwise chains the compiler fuses into a single HBM pass (the
reference's hand-written mshadow kernels).

Pure-functional convention: the reference mutates state inputs (mom,
mean/var, z/n...) in place and returns the weight; these ops return
``(out_weight, *updated_states)`` instead. The nd-layer wrappers in
`mxnet_tpu.optimizer.ops` restore the mutate-in-place call surface for
API compatibility.

Clip convention throughout (matching dmlc param docs): clip_gradient
< 0 disables clipping.
"""
from __future__ import annotations

import os

import jax.numpy as jnp

from .registry import register


def _clip(g, c):
    # clip bounds are static op attrs, so this resolves at trace time
    if c is None or c < 0:
        return g
    return jnp.clip(g, -c, c)


def _fused_kernel_enabled():
    """MXTPU_KERNEL_FUSED_OPT: route sgd_mom/adam through the Pallas
    one-pass update kernel (ops/pallas_kernels.fused_*). ``auto``
    (default) = chip backends only — the jnp path below IS the CPU hot
    path and the kernel's numerics oracle, so behavior off-chip is
    unchanged. Resolves at trace time (static env read, no tracer
    impurity)."""
    v = os.environ.get("MXTPU_KERNEL_FUSED_OPT", "auto").lower()
    if v in ("0", "off", "false", "no"):
        return False
    if v in ("1", "on", "true", "yes"):
        return True
    import jax
    return jax.default_backend() in ("tpu", "axon")


# ---------------------------------------------------------------------------
# SGD family (ref: optimizer_op-inl.h SGDKernel / SGDMomKernel)
# ---------------------------------------------------------------------------


@register("sgd_update")
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """out = (1 - lr*wd)*w - lr*clip(rescale*g)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    return (1.0 - lr * wd) * weight - lr * g


@register("sgd_mom_update", num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """mom' = mu*mom - lr*wd*w - lr*clip(rescale*g); out = w + mom'."""
    if _fused_kernel_enabled():
        from .pallas_kernels import fused_sgd_mom
        return fused_sgd_mom(weight, grad, mom, lr=lr,
                             momentum=momentum, wd=wd,
                             rescale_grad=rescale_grad,
                             clip_gradient=clip_gradient)
    g = _clip(rescale_grad * grad, clip_gradient)
    mom = momentum * mom - lr * wd * weight - lr * g
    return weight + mom, mom


@register("mp_sgd_update", num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: the update runs on the fp32 master copy, the
    low-precision weight output is a cast of it (ref: optimizer_op-inl.h
    MP_SGDKernel)."""
    g = _clip(rescale_grad * grad.astype(jnp.float32), clip_gradient)
    w32 = (1.0 - lr * wd) * weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _clip(rescale_grad * grad.astype(jnp.float32), clip_gradient)
    mom = momentum * mom - lr * wd * weight32 - lr * g
    w32 = weight32 + mom
    return w32.astype(weight.dtype), mom, w32


@register("signsgd_update")
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    """out = (1 - lr*wd)*w - lr*sign(g); clip has no effect (ref:
    SignSGDKernel comment)."""
    return (1.0 - lr * wd) * weight - lr * jnp.sign(grad)


@register("signum_update", num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """mom' = mu*mom - (1-mu)*(wd*w + clip(rescale*g));
    out = (1 - lr*wd_lh)*w + lr*sign(mom') (ref: SignumKernel)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    mom = momentum * mom - (1.0 - momentum) * wd * weight \
        - (1.0 - momentum) * g
    return (1.0 - lr * wd_lh) * weight + lr * jnp.sign(mom), mom


# ---------------------------------------------------------------------------
# Adam / FTML / FTRL (ref: optimizer_op-inl.h AdamUpdate/FTMLKernel/
# FtrlUpdate)
# ---------------------------------------------------------------------------


@register("adam_update", num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.01, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """No in-kernel bias correction — the Python optimizer folds it into
    lr, matching the reference kernel exactly."""
    if _fused_kernel_enabled():
        from .pallas_kernels import fused_adam
        return fused_adam(weight, grad, mean, var, lr=lr, beta1=beta1,
                          beta2=beta2, epsilon=epsilon, wd=wd,
                          rescale_grad=rescale_grad,
                          clip_gradient=clip_gradient)
    g = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * g
    var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    out = weight - lr * mean / (jnp.sqrt(var) + epsilon)
    return out, mean, var


@register("ftml_update", num_outputs=4)
def ftml_update(weight, grad, d, v, z, lr=0.01, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    g = _clip(rescale_grad * grad + wd * weight, clip_grad)
    v_new = beta2 * v + (1.0 - beta2) * jnp.square(g)
    d_t = (1.0 - beta1 ** t) / lr * (
        jnp.sqrt(v_new / (1.0 - beta2 ** t)) + epsilon)
    z_new = beta1 * z + (1.0 - beta1) * g - (d_t - beta1 * d) * weight
    return -z_new / d_t, d_t, v_new, z_new


@register("ftrl_update", num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _clip(rescale_grad * grad, clip_gradient)
    z_new = z + g - (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) * weight / lr
    n_new = n + jnp.square(g)
    out = (jnp.sign(z_new) * lamda1 - z_new) / (
        (beta + jnp.sqrt(n_new)) / lr + wd) * (jnp.abs(z_new) > lamda1)
    return out, z_new, n_new


# ---------------------------------------------------------------------------
# RMSProp (ref: optimizer_op-inl.h RMSPropUpdate / RMSPropAlexUpdate)
# ---------------------------------------------------------------------------


@register("rmsprop_update", num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.01, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    """Tieleman & Hinton non-centered RMSProp."""
    g = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    n_new = (1.0 - gamma1) * jnp.square(g) + gamma1 * n
    out = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights >= 0:
        out = jnp.clip(out, -clip_weights, clip_weights)
    return out, n_new


@register("rmspropalex_update", num_outputs=4)
def rmspropalex_update(weight, grad, n, g, delta, lr=0.01, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Graves 2013 centered RMSProp with momentum."""
    gr = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    n_new = (1.0 - gamma1) * jnp.square(gr) + gamma1 * n
    g_new = (1.0 - gamma1) * gr + gamma1 * g
    delta_new = gamma2 * delta - lr * gr / jnp.sqrt(
        n_new - jnp.square(g_new) + epsilon)
    out = weight + delta_new
    if clip_weights >= 0:
        out = jnp.clip(out, -clip_weights, clip_weights)
    return out, n_new, g_new, delta_new


# ---------------------------------------------------------------------------
# AdaGrad (ref: optimizer_op-inl.h AdagradDnsRspDnsKernel — registered as
# _sparse_adagrad_update; contrib/optimizer_op-inl.h GroupAdagrad)
# ---------------------------------------------------------------------------


@register("_sparse_adagrad_update", num_outputs=2)
def sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Dense lowering of the rsp kernel: rows absent from a row-sparse
    gradient have g=0 so h and w are unchanged — the dense form computes
    the same fixpoint. RowSparse callers go through
    Optimizer AdaGrad's row-granular path."""
    g = _clip(rescale_grad * grad, clip_gradient)
    h_new = history + jnp.square(g)
    return weight - lr * g / jnp.sqrt(h_new + epsilon), h_new


@register("_contrib_group_adagrad_update", num_outputs=2,
          aliases=("group_adagrad_update",))
def group_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-5,
                         rescale_grad=1.0, clip_gradient=-1.0):
    """Per-row (group) accumulator: h_row += mean(g_row^2); every element
    of the row divides by the same sqrt(h_row+eps) (ref: contrib
    GroupAdagradKernel state update `grad_ssq / row_length`)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    red_axes = tuple(range(1, g.ndim))
    h_new = history + jnp.mean(jnp.square(g), axis=red_axes).reshape(
        history.shape)
    denom = jnp.sqrt(
        h_new.reshape((-1,) + (1,) * (g.ndim - 1)) + epsilon)
    return weight - lr * g / denom, h_new
