"""Neural-network ops: the MXU-heavy core of the framework.

Mirrors src/operator/nn/*.cc (Convolution, FullyConnected, BatchNorm, Pooling,
Activation, Dropout, LRN, LayerNorm, UpSampling, Softmax...). Where the
reference dispatches to MKL-DNN primitives with opaque blocked layouts
(src/operator/nn/mkldnn/), this framework lowers every op to XLA HLO:
convolutions/matmuls hit the MXU via lax.conv_general_dilated / dot_general,
and surrounding elementwise work is fused by XLA — the conv+bn+relu fusion the
reference implements by hand in its subgraph backend falls out of the compiler
here (and is *verified* by the subgraph tests rather than hand-scheduled).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from .registry import register

# ---------------------------------------------------------------------------
# FullyConnected (ref: src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------


@register("FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    x = data.reshape(data.shape[0], -1) if flatten else data
    # weight layout (num_hidden, in_units) as in the reference
    # bf16 operands ride the MXU, which accumulates in fp32 internally;
    # requesting an f32 output via preferred_element_type would break
    # the VJP (the transpose rule feeds the f32 cotangent into a conv
    # with bf16 operands) so the output stays in the input dtype
    out = lax.dot_general(
        x, weight,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())))
    if not no_bias and bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (ref: src/operator/nn/convolution.cc)
# ---------------------------------------------------------------------------

_CONV_DNUMS = {1: ("NCH", "OIH", "NCH"),
               2: ("NCHW", "OIHW", "NCHW"),
               3: ("NCDHW", "OIDHW", "NCDHW")}

# channel-last data layouts (TPU-preferred: C rides the 128-lane minor
# dim so XLA needs no relayout copies around each conv — the analogue of
# the reference's MKL-DNN blocked layouts, src/ndarray/ndarray.cc:389).
# Weights stay in the reference's OIHW storage convention either way;
# dnums tell XLA where C lives, so no weight transpose materializes.
_CHANNEL_LAST = {"NWC": "H", "NHWC": "HW", "NDHWC": "DHW"}


_CHANNEL_FIRST = {"NCW": 1, "NCHW": 2, "NCDHW": 3}


def _conv_layout(layout, nd):
    """(data_spec, weight_spec, channel_axis) for an MXNet layout string."""
    default = _CONV_DNUMS[nd][0]
    if layout is None or layout == default \
            or _CHANNEL_FIRST.get(layout) == nd:
        # MXNet spells 1-d channel-first "NCW"; the jax spec uses "NCH"
        return _CONV_DNUMS[nd] + (1,)
    spatial = _CHANNEL_LAST.get(layout)
    if spatial is None or len(spatial) != nd:
        raise MXNetError(f"Convolution: unsupported layout {layout!r} "
                         f"for {nd}-d kernel")
    spec = "N" + spatial + "C"
    return (spec, "OI" + spatial, spec, nd + 1)


@register("Convolution")
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                layout=None, workspace=1024, cudnn_tune=None,
                cudnn_off=False):
    nd = len(kernel)
    stride = tuple(stride) or (1,) * nd
    dilate = tuple(dilate) or (1,) * nd
    pad = tuple(pad) or (0,) * nd
    lhs_spec, w_spec, out_spec, c_axis = _conv_layout(layout, nd)
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=tuple((p, p) for p in pad),
        rhs_dilation=dilate,
        dimension_numbers=(lhs_spec, w_spec, out_spec),
        feature_group_count=num_group,
    ).astype(data.dtype)
    if not no_bias and bias is not None:
        bshape = tuple(-1 if i == c_axis else 1 for i in range(nd + 2))
        out = out + bias.reshape(bshape)
    return out


@register("_contrib_s2d_stem_conv")
def s2d_stem_conv(data, weight, stride=2, pad=3, block=2, layout="NCHW"):
    """Space-to-depth stem convolution (the MLPerf ResNet TPU trick).

    A KxK stride-s conv on a C_in=3 image runs the MXU at <3% lane
    utilization (3 input channels vs 128 lanes). Rearranging the input
    into bxb blocks (space-to-depth) and the SAME OIHW weight into an
    equivalent (K/b)x(K/b) conv over C_in*b*b channels computes the
    identical result with b*b-fold better lane utilization. The weight
    stays in the reference's OIHW storage convention — the rearrange is
    part of the graph, so checkpoints interoperate freely with the
    standard stem. (ref analogue: the reference reorders weights into
    MKL-DNN blocked layouts at the same seam, mkldnn_base-inl.h
    GetWeights; here the 'blocked layout' is the s2d form.)
    """
    O, C, KH, KW = weight.shape
    b = int(block)
    s = int(stride)
    p = int(pad)
    if s % b != 0:
        raise MXNetError("s2d stem: block must divide stride")
    front = (-KH) % b
    if (p + front) % b != 0:
        # exact equivalence needs the blocked window start b*(t*sp - pl)
        # to equal the reference's t*s - (p + front) — i.e. b | (p+front).
        # Flooring pl instead would silently shift every output pixel.
        raise MXNetError(
            "s2d stem: pad %d with kernel %d is not block-%d alignable"
            % (p, KH, b))
    w8 = jnp.pad(weight, ((0, 0), (0, 0), (front, 0), (front, 0)))
    K8 = KH + front
    Kp = K8 // b
    # (O, C, kh', py, kw', px) -> (O, py, px, C, kh', kw') -> OIHW'
    wp = w8.reshape(O, C, Kp, b, Kp, b).transpose(0, 3, 5, 1, 2, 4) \
        .reshape(O, C * b * b, Kp, Kp)

    # reuse the standard layout table so bad layout strings raise
    # instead of silently computing on the wrong axes
    lhs_spec, _w_spec, out_spec, c_axis = _conv_layout(layout, 2)
    channel_last = c_axis == 3
    if channel_last:
        N, H, W, _ = data.shape
        xp = data.reshape(N, H // b, b, W // b, b, C) \
            .transpose(0, 1, 3, 2, 4, 5) \
            .reshape(N, H // b, W // b, b * b * C)
    else:
        N, _, H, W = data.shape
        xp = data.reshape(N, C, H // b, b, W // b, b) \
            .transpose(0, 3, 5, 1, 2, 4) \
            .reshape(N, C * b * b, H // b, W // b)

    sp = s // b
    pl = (p + front) // b
    # per-axis right pad: pr only cancels across axes when stride==block
    def _pr(size):
        out_sz = (size + 2 * p - KH) // s + 1
        return (out_sz - 1) * sp + Kp - size // b - pl

    out = lax.conv_general_dilated(
        xp, wp, (sp, sp), ((pl, _pr(H)), (pl, _pr(W))),
        dimension_numbers=(lhs_spec, "OIHW", out_spec),
    ).astype(data.dtype)
    return out


@register("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=0, num_group=1,
                  no_bias=True, layout=None, workspace=1024, cudnn_tune=None,
                  cudnn_off=False):
    if layout in _CHANNEL_LAST:
        raise MXNetError(
            f"Deconvolution: channel-last layout {layout!r} not supported")
    nd = len(kernel)
    stride = tuple(stride) or (1,) * nd
    pad = tuple(pad) or (0,) * nd
    adj = tuple(adj) or (0,) * nd
    # transposed conv == gradient of conv wrt input: lhs-dilate by stride.
    # weight layout (in_ch, out_ch/group, *k) per the reference; flip spatial
    # dims and swap io to express as a regular conv.
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if num_group > 1:
        cin, cog = w.shape[0], w.shape[1]
        w = w.reshape((num_group, cin // num_group) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((num_group * cog, cin // num_group) + w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    k = tuple(kernel)
    padding = tuple(
        (k[i] - 1 - pad[i], k[i] - 1 - pad[i] + adj[i]) for i in range(nd)
    )
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        dimension_numbers=_CONV_DNUMS[nd],
        feature_group_count=num_group,
    ).astype(data.dtype)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (ref: src/operator/nn/pooling.cc)
# ---------------------------------------------------------------------------


@register("Pooling")
def pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
            global_pool=False, pooling_convention="valid", cudnn_off=False,
            p_value=2, count_include_pad=True, layout=None):
    nd = data.ndim - 2
    channel_last = layout in _CHANNEL_LAST
    # spatial dims: 2..ndim-1 for NC-first, 1..ndim-2 for channel-last
    sp0 = 1 if channel_last else 2
    spatial_axes = tuple(range(sp0, sp0 + nd))
    if global_pool:
        kernel = tuple(data.shape[a] for a in spatial_axes)
        stride = (1,) * nd
        pad = (0,) * nd
    kernel = tuple(kernel)
    stride = tuple(stride) or (1,) * nd
    pad = tuple(pad) or (0,) * nd

    def _place(vals, fill):
        out = [fill] * data.ndim
        for a, v in zip(spatial_axes, vals):
            out[a] = v
        return tuple(out)

    window = _place(kernel, 1)
    strides = _place(stride, 1)
    if pooling_convention == "full":
        # ceil-mode: pad on the high side so the last partial window counts
        pads = []
        for i in range(nd):
            in_i = data.shape[spatial_axes[i]] + 2 * pad[i]
            rem = (in_i - kernel[i]) % stride[i]
            extra = (stride[i] - rem) % stride[i] if in_i > kernel[i] else 0
            pads.append((pad[i], pad[i] + extra))
    else:
        pads = [(p, p) for p in pad]
    padding = _place(pads, (0, 0))

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        powd = jnp.power(jnp.abs(data), p_value)
        summed = lax.reduce_window(powd, 0.0, lax.add, window, strides, padding)
        return jnp.power(summed, 1.0 / p_value)
    raise MXNetError(f"pool_type {pool_type!r} unsupported")


# ---------------------------------------------------------------------------
# Activations (ref: src/operator/nn/activation.cc, ../leaky_relu.cc)
# ---------------------------------------------------------------------------


@register("Activation")
def activation(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jnp.log1p(jnp.exp(-jnp.abs(data))) + jnp.maximum(data, 0)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise MXNetError(f"act_type {act_type!r} unsupported")


@register("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if data.ndim > 2 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        # eval-mode rrelu uses the mean slope (train-mode randomness lives in
        # the layer, which passes an explicit slope)
        return jnp.where(data >= 0, data, (lower_bound + upper_bound) / 2 * data)
    raise MXNetError(f"LeakyReLU act_type {act_type!r} unsupported")


@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None):
    x = data / temperature if temperature else data
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register("SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


import functools


@functools.lru_cache(maxsize=None)
def _softmax_output_closure(grad_scale, ignore_label, use_ignore, multi_output,
                            normalization, smooth_alpha):
    axis = 1 if multi_output else -1

    def fwd(data, label):
        return jax.nn.softmax(data, axis=axis)

    @jax.custom_vjp
    def f(data, label):
        return fwd(data, label)

    def f_fwd(data, label):
        out = fwd(data, label)
        return out, (out, label)

    def f_bwd(res, g):
        """The reference's signature trick (src/operator/softmax_output-inl.h):
        grad wrt data is (softmax - onehot(label)) * grad_scale, independent
        of the incoming head gradient."""
        out, label = res
        nclass = out.shape[axis]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), nclass, axis=axis,
                                dtype=out.dtype)
        if smooth_alpha:
            onehot = onehot * (1 - smooth_alpha) + \
                smooth_alpha / (nclass - 1) * (1 - onehot)
        grad = out - onehot
        if use_ignore:
            keep = (label != ignore_label).astype(out.dtype)
            grad = grad * jnp.expand_dims(keep, axis)
        scale = grad_scale
        if normalization == "batch":
            scale = scale / out.shape[0]
        elif normalization == "valid" and use_ignore:
            nvalid = jnp.maximum(jnp.sum(label != ignore_label), 1)
            scale = scale / nvalid
        return grad * scale, jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return f


@register("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   use_ignore=False, multi_output=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    if not multi_output and label.ndim == data.ndim and \
            label.shape[-1] == 1:
        label = label.reshape(label.shape[:-1])  # (N,1) labels, as CSVIter
    f = _softmax_output_closure(grad_scale, ignore_label, use_ignore,
                                multi_output, normalization, smooth_alpha)
    return f(data, label)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


def _regression_closure(grad_scale, fwd, bwd):
    """Loss-layer contract shared by the regression heads (ref:
    src/operator/regression_output-inl.h:190-206): forward transforms the
    data, backward REPLACES the head gradient with
    BackwardOp(out, label) * grad_scale / num_output."""

    @jax.custom_vjp
    def f(data, label):
        return fwd(data)

    def f_fwd(data, label):
        out = fwd(data)
        return out, (out, label)

    def f_bwd(res, g):
        out, label = res
        lab = label.reshape(out.shape) if label.size == out.size \
            else jnp.broadcast_to(label.reshape(label.shape + (1,) * (
                out.ndim - label.ndim)), out.shape)
        num_output = max(int(np.prod(out.shape[1:])), 1)
        grad = bwd(out, lab) * (grad_scale / num_output)
        return grad.astype(out.dtype), jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return f


@functools.lru_cache(maxsize=None)
def _linear_reg_closure(grad_scale):
    return _regression_closure(grad_scale, lambda d: d, lambda o, l: o - l)


@functools.lru_cache(maxsize=None)
def _mae_reg_closure(grad_scale):
    return _regression_closure(grad_scale, lambda d: d,
                               lambda o, l: jnp.sign(o - l))


@functools.lru_cache(maxsize=None)
def _logistic_reg_closure(grad_scale):
    return _regression_closure(grad_scale, jax.nn.sigmoid,
                               lambda o, l: o - l)


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    return _linear_reg_closure(float(grad_scale))(data, label)


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    return _mae_reg_closure(float(grad_scale))(data, label)


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    return _logistic_reg_closure(float(grad_scale))(data, label)


# ---------------------------------------------------------------------------
# Normalization (ref: src/operator/nn/batch_norm.cc, layer_norm.cc,
# ../instance_norm.cc, ../l2_normalization.cc)
# ---------------------------------------------------------------------------


@register("BatchNorm")
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, training=False):
    """Normalize; batch statistics when training (moving-stat update is
    managed functionally by the BatchNorm layer / executor, since this op is
    pure — the reference mutates aux states in-place instead)."""
    axis = axis % data.ndim
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1 for i in range(data.ndim))
    if training and not use_global_stats:
        mean = jnp.mean(data, axis=reduce_axes)
        var = jnp.var(data, axis=reduce_axes)
    else:
        mean, var = moving_mean, moving_var
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape)) * (inv * g).reshape(bshape) + beta.reshape(bshape)
    # fp32 gamma/beta/stats with fp16/bf16 data must not widen the graph
    # downstream — the reference's BN kernel emits data-dtype output
    # while keeping its parameters fp32 (mixed-precision contract)
    out = out.astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    ax = axis % data.ndim
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    out = (data - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    return out


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    ax = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) + beta.reshape(bshape)


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    sq = jnp.square(data)
    half = nsize // 2
    summed = lax.reduce_window(
        sq, 0.0, lax.add,
        window_dimensions=(1, nsize, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (half, half), (0, 0), (0, 0)),
    )
    return data / jnp.power(knorm + alpha / nsize * summed, beta)


# ---------------------------------------------------------------------------
# Dropout (ref: src/operator/nn/dropout.cc) — RNG op: key injected by runtime
# ---------------------------------------------------------------------------


@register("Dropout", needs_rng=True)
def dropout(key, data, p=0.5, mode="training", axes=(), training=True,
            cudnn_off=False):
    if (not training and mode != "always") or p <= 0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(data.shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype) / keep
    return data * mask


# ---------------------------------------------------------------------------
# UpSampling / resize (ref: src/operator/nn/upsampling.cc,
# contrib/bilinear_resize.cc)
# ---------------------------------------------------------------------------


@register("UpSampling", num_inputs=None)
def upsampling(*args, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    data = args[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        if num_args > 1 and multi_input_mode == "concat":
            outs = [out]
            for extra in args[1:]:
                s = out.shape[2] // extra.shape[2]
                outs.append(jnp.repeat(jnp.repeat(extra, s, axis=2), s, axis=3))
            out = jnp.concatenate(outs, axis=1)
        return out
    if sample_type == "bilinear":
        weight = args[1] if len(args) > 1 else None
        n, c, h, w = data.shape
        return jax.image.resize(data, (n, c, h * scale, w * scale), "bilinear")
    raise MXNetError(f"sample_type {sample_type!r} unsupported")


@register("CTCLoss", aliases=("ctc_loss", "_contrib_CTCLoss"),
          optional_arrays=("data_lengths", "label_lengths"))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Connectionist Temporal Classification loss
    (ref: src/operator/nn/ctc_loss.cc:51 — warp-ctc semantics).

    data: (seq_len, batch, alphabet) pre-softmax activations.
    label: (batch, max_label_len) int indices. When blank_label is
    "first" blank is id 0, labels use 1..alphabet-1 and padding is 0;
    when "last" blank is alphabet-1 and padding is -1.

    Lowering: optax.ctc_loss (the same log-semiring scan the reference's
    warp-ctc computes), with MXNet's length flags mapped onto optax's
    padding masks; differentiable through jax autodiff.
    """
    import optax

    # dispatch quirk: optional array inputs bind positionally in
    # signature order, so a call providing only label_lengths arrives
    # in the data_lengths slot — rebind using the use_* flags
    if (use_label_lengths and label_lengths is None
            and data_lengths is not None and not use_data_lengths):
        label_lengths, data_lengths = data_lengths, None
    if use_data_lengths and data_lengths is None:
        raise ValueError("CTCLoss: use_data_lengths=True but no "
                         "data_lengths array was provided")
    if use_label_lengths and label_lengths is None:
        raise ValueError("CTCLoss: use_label_lengths=True but no "
                         "label_lengths array was provided (when both "
                         "use_* flags are set, both arrays are required)")

    T, B, A = data.shape
    L = label.shape[1]
    logits = jnp.swapaxes(data.astype(jnp.float32), 0, 1)  # (B, T, A)
    lab = label.astype(jnp.int32)
    blank = 0 if blank_label == "first" else A - 1
    pad_mask = (lab == 0) if blank_label == "first" else (lab < 0)

    if use_data_lengths and data_lengths is not None:
        steps = jnp.arange(T)[None, :]
        logit_pad = (steps >= data_lengths.astype(jnp.int32)
                     .reshape(B)[:, None]).astype(jnp.float32)
    else:
        logit_pad = jnp.zeros((B, T), jnp.float32)

    if L == 0:
        # empty label set: P = all-blank path over the unpadded frames
        lp = jax.nn.log_softmax(logits, axis=-1)[:, :, blank]
        return -jnp.sum(lp * (1.0 - logit_pad), axis=1)

    if use_label_lengths and label_lengths is not None:
        steps = jnp.arange(L)[None, :]
        label_pad = (steps >= label_lengths.astype(jnp.int32)
                     .reshape(B)[:, None]).astype(jnp.float32)
    else:
        label_pad = pad_mask.astype(jnp.float32)
    # padded entries must hold a valid non-negative index; they are
    # masked by label_pad, the value itself is irrelevant
    lab = jnp.where(label_pad > 0, 0, lab)
    return optax.ctc_loss(logits, logit_pad, lab, label_pad,
                          blank_id=blank)


@register("_contrib_BilinearResize2D")
def bilinear_resize_2d(data, height=1, width=1, scale_height=None,
                       scale_width=None, mode="size"):
    n, c, h, w = data.shape
    if scale_height is not None:
        height, width = int(h * scale_height), int(w * scale_width)
    return jax.image.resize(data, (n, c, height, width), "bilinear")


@register("_contrib_AdaptiveAvgPooling2D")
def adaptive_avg_pooling(data, output_size=(1, 1)):
    os = output_size if isinstance(output_size, (tuple, list)) else (output_size, output_size)
    n, c, h, w = data.shape
    if h % os[0] == 0 and w % os[1] == 0:
        kh, kw = h // os[0], w // os[1]
        x = data.reshape(n, c, os[0], kh, os[1], kw)
        return jnp.mean(x, axis=(3, 5))

    # non-divisible case: per-window means with floor/ceil boundaries,
    # expressed separably as two small matmuls (static shapes)
    def win_matrix(in_len, out_len):
        m = np.zeros((out_len, in_len), np.float32)
        for o in range(out_len):
            s = (o * in_len) // out_len
            e = -(-((o + 1) * in_len) // out_len)  # ceil div
            m[o, s:e] = 1.0 / (e - s)
        return jnp.asarray(m)

    rw = win_matrix(h, os[0])
    cw = win_matrix(w, os[1])
    return jnp.einsum("oh,nchw,pw->ncop", rw, data, cw)


# ---------------------------------------------------------------------------
# Correlation (ref: src/operator/correlation-inl.h:80-130) — FlowNet-style
# cost volume between two feature maps.
# ---------------------------------------------------------------------------


@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Patch correlation of data1 against displaced data2 neighborhoods.

    The reference launches one CUDA block per displacement; here the D*D
    displacement grid is a static Python loop of shifted elementwise
    products, each reduced over the kernel window with reduce_window and
    over channels — every step is an XLA-fusable dense op, and the MXU sees
    the surrounding convs, not this (it is bandwidth-bound by design).
    Normalization matches the reference: sumelems = K*K*C.
    """
    n, c, h, w = data1.shape
    pb_h, pb_w = h + 2 * pad_size, w + 2 * pad_size
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    top_h = -(-(pb_h - 2 * border) // stride1)
    top_w = -(-(pb_w - 2 * border) // stride1)
    ngr = max_displacement // stride2     # neighborhood grid radius
    pad = ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size))
    p1 = jnp.pad(data1, pad)
    p2 = jnp.pad(data2, pad)
    sumelems = kernel_size * kernel_size * c
    planes = []
    for dy in range(-ngr, ngr + 1):
        for dx in range(-ngr, ngr + 1):
            sy, sx = dy * stride2, dx * stride2
            shifted = jnp.roll(p2, (-sy, -sx), axis=(2, 3))
            prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
            summed = jnp.sum(prod, axis=1)  # over channels -> (n, pbh, pbw)
            if kernel_size > 1:
                summed = lax.reduce_window(
                    summed, 0.0, lax.add, (1, kernel_size, kernel_size),
                    (1, 1, 1), "SAME")
            # top-left output sample sits at the border offset
            win = lax.dynamic_slice(
                summed, (0, border, border),
                (n, pb_h - 2 * border, pb_w - 2 * border))
            planes.append(win[:, ::stride1, ::stride1][:, :top_h, :top_w])
    out = jnp.stack(planes, axis=1) / sumelems
    return out.astype(data1.dtype)


# ---------------------------------------------------------------------------
# SVMOutput (ref: src/operator/svm_output.cc:31-66) — hinge-loss output
# layer: forward is identity, backward replaces the head gradient with the
# L1/L2 SVM subgradient (the same "loss layer defines its own gradient"
# contract as SoftmaxOutput).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _svm_output_closure(margin, regularization_coefficient, use_linear):
    reg = regularization_coefficient

    @jax.custom_vjp
    def f(data, label):
        return data

    def f_fwd(data, label):
        return data, (data, label)

    def f_bwd(res, g):
        out, label = res
        k = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(k, out.shape[1], dtype=out.dtype)
        if use_linear:  # L1_SVM (svm_output.cc:31-46)
            g_true = -(margin > out).astype(out.dtype) * reg
            g_other = (margin > -out).astype(out.dtype) * reg
        else:           # L2_SVM (svm_output.cc:49-66)
            g_true = -reg * jnp.where(margin > out, 2 * (margin - out), 0.0)
            g_other = -reg * jnp.where(margin > -out, -2 * (margin + out), 0.0)
        grad = jnp.where(onehot > 0, g_true, g_other).astype(out.dtype)
        return grad, jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return f


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    if label.ndim == data.ndim and label.shape[-1] == 1:
        label = label.reshape(label.shape[:-1])
    flat = data.reshape(data.shape[0], -1)
    f = _svm_output_closure(float(margin), float(regularization_coefficient),
                            bool(use_linear))
    return f(flat, label).reshape(data.shape)


# ---------------------------------------------------------------------------
# legacy v1/compat ops (ref: src/operator/batch_norm_v1.cc,
# convolution_v1.cc, pooling_v1.cc, crop.cc, swapaxis.cc — deprecated
# spellings the reference still registers; they alias the modern
# implementations, whose math is a superset)
# ---------------------------------------------------------------------------


@register("BatchNorm_v1")
def batch_norm_v1(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                  momentum=0.9, fix_gamma=True, use_global_stats=False,
                  output_mean_var=False, training=False):
    return batch_norm(data, gamma, beta, moving_mean, moving_var, eps=eps,
                      momentum=momentum, fix_gamma=fix_gamma,
                      use_global_stats=use_global_stats,
                      output_mean_var=output_mean_var, training=training)


@register("Convolution_v1")
def convolution_v1(data, weight, bias=None, kernel=(), stride=(),
                   dilate=(), pad=(), num_filter=0, num_group=1,
                   workspace=1024, no_bias=False, layout=None):
    return convolution(data, weight, bias, kernel=kernel, stride=stride,
                       dilate=dilate, pad=pad, num_filter=num_filter,
                       num_group=num_group, no_bias=no_bias)


@register("Pooling_v1")
def pooling_v1(data, kernel=(), pool_type="max", global_pool=False,
               stride=(), pad=(), pooling_convention="valid"):
    return pooling(data, kernel=kernel, pool_type=pool_type,
                   global_pool=global_pool, stride=stride, pad=pad,
                   pooling_convention=pooling_convention)


@register("Crop", optional_arrays=("crop_like",))
def legacy_crop(data, crop_like=None, offset=(0, 0), h_w=(0, 0),
                center_crop=False, num_args=1):
    """Legacy spatial Crop (ref: src/operator/crop-inl.h:47-62): crop
    NCHW `data` to `h_w` (or to `crop_like`'s spatial dims), at `offset`
    or centered."""
    H, W = data.shape[2], data.shape[3]
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        y0 = max((H - th) // 2, 0)
        x0 = max((W - tw) // 2, 0)
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    if y0 + th > H or x0 + tw > W or y0 < 0 or x0 < 0:
        raise MXNetError(
            f"Crop: window offset ({y0},{x0}) size ({th},{tw}) exceeds "
            f"input ({H},{W}) (the reference CHECKs the same at crop-inl.h)")
    return data[:, :, y0:y0 + th, x0:x0 + tw]
