"""Random sampling + init ops.

Mirrors src/operator/random/sample_op.cc and src/operator/tensor/init_op.cc.
RNG design: jax's counter-based PRNG replaces the reference's per-device
mt19937/Philox state arrays (include/mxnet/random_generator.h) — a global
seedable key chain lives in mxnet_tpu.random; each RNG op receives a fresh
subkey as its first array argument (recorded on the autograd tape, so replay
is bit-deterministic).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import register


def _dt(dtype):
    return jnp.dtype(dtype if dtype not in (None, "None") else "float32")


# -- init ops ---------------------------------------------------------------

@register("_zeros", aliases=("zeros",))
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(tuple(shape), _dt(dtype))


@register("_ones", aliases=("ones",))
def _ones(shape=(), dtype="float32"):
    return jnp.ones(tuple(shape), _dt(dtype))


@register("_full", aliases=("full",))
def _full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(tuple(shape), value, _dt(dtype))


@register("_arange", aliases=("arange",))
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, _dt(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_eye", aliases=("eye",))
def _eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(N, M or None, k, dtype=_dt(dtype))


# -- samplers ---------------------------------------------------------------

@register("_random_uniform", aliases=("random_uniform", "uniform"), needs_rng=True)
def random_uniform(key, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(key, tuple(shape), _dt(dtype), low, high)


@register("_random_normal", aliases=("random_normal", "normal"), needs_rng=True)
def random_normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(key, tuple(shape), _dt(dtype))


@register("_random_gamma", aliases=("random_gamma",), needs_rng=True)
def random_gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return jax.random.gamma(key, alpha, tuple(shape), _dt(dtype)) * beta


@register("_random_exponential", aliases=("random_exponential",), needs_rng=True)
def random_exponential(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(key, tuple(shape), _dt(dtype)) / lam


@register("_random_poisson", aliases=("random_poisson",), needs_rng=True)
def random_poisson(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(key, lam, tuple(shape)).astype(_dt(dtype))


@register("_random_randint", aliases=("random_randint",), needs_rng=True)
def random_randint(key, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(key, tuple(shape), low, high, _dt(dtype))


@register("_random_negative_binomial", aliases=("random_negative_binomial",),
          needs_rng=True)
def random_negative_binomial(key, k=1, p=0.5, shape=(), dtype="float32"):
    # NB(k, p) == Poisson(Gamma(k, (1-p)/p))
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, tuple(shape)) * ((1 - p) / p)
    return jax.random.poisson(kp, lam, tuple(shape)).astype(_dt(dtype))


@register("_random_generalized_negative_binomial",
          aliases=("random_generalized_negative_binomial",), needs_rng=True)
def random_gnb(key, mu=1.0, alpha=1.0, shape=(), dtype="float32"):
    kg, kp = jax.random.split(key)
    if alpha == 0:
        return jax.random.poisson(kp, mu, tuple(shape)).astype(_dt(dtype))
    lam = jax.random.gamma(kg, 1.0 / alpha, tuple(shape)) * (alpha * mu)
    return jax.random.poisson(kp, lam, tuple(shape)).astype(_dt(dtype))


# *_like samplers: shape/dtype follow the input array
# (ref: src/operator/random/sample_op.cc *_like registrations)

@register("_random_uniform_like", aliases=("random_uniform_like",),
          needs_rng=True)
def random_uniform_like(key, data, low=0.0, high=1.0):
    return jax.random.uniform(key, data.shape, data.dtype, low, high)


@register("_random_normal_like", aliases=("random_normal_like",),
          needs_rng=True)
def random_normal_like(key, data, loc=0.0, scale=1.0):
    return loc + scale * jax.random.normal(key, data.shape, data.dtype)


@register("_random_gamma_like", aliases=("random_gamma_like",),
          needs_rng=True)
def random_gamma_like(key, data, alpha=1.0, beta=1.0):
    return jax.random.gamma(key, alpha, data.shape, data.dtype) * beta


@register("_random_exponential_like", aliases=("random_exponential_like",),
          needs_rng=True)
def random_exponential_like(key, data, lam=1.0):
    return jax.random.exponential(key, data.shape, data.dtype) / lam


@register("_random_poisson_like", aliases=("random_poisson_like",),
          needs_rng=True)
def random_poisson_like(key, data, lam=1.0):
    return jax.random.poisson(key, lam, data.shape).astype(data.dtype)


@register("_random_negative_binomial_like",
          aliases=("random_negative_binomial_like",), needs_rng=True)
def random_negative_binomial_like(key, data, k=1, p=0.5):
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, data.shape) * ((1 - p) / p)
    return jax.random.poisson(kp, lam, data.shape).astype(data.dtype)


@register("_random_generalized_negative_binomial_like",
          aliases=("random_generalized_negative_binomial_like",),
          needs_rng=True)
def random_gnb_like(key, data, mu=1.0, alpha=1.0):
    kg, kp = jax.random.split(key)
    if alpha == 0:
        return jax.random.poisson(kp, mu, data.shape).astype(data.dtype)
    lam = jax.random.gamma(kg, 1.0 / alpha, data.shape) * (alpha * mu)
    return jax.random.poisson(kp, lam, data.shape).astype(data.dtype)


# sample_* ops: per-element distribution parameters given as input arrays
# (ref: src/operator/random/multisample_op.cc)


def _bcast(param, extra_ndim):
    return param.reshape(tuple(param.shape) + (1,) * extra_ndim)

@register("_sample_uniform", aliases=("sample_uniform",), needs_rng=True)
def sample_uniform(key, low, high, shape=(), dtype="float32"):
    s = tuple(low.shape) + tuple(shape)
    u = jax.random.uniform(key, s, _dt(dtype))
    ext = u.ndim - low.ndim
    bl = low.reshape(low.shape + (1,) * ext)
    bh = high.reshape(high.shape + (1,) * ext)
    return bl + u * (bh - bl)


@register("_sample_normal", aliases=("sample_normal",), needs_rng=True)
def sample_normal(key, mu, sigma, shape=(), dtype="float32"):
    s = tuple(mu.shape) + tuple(shape)
    n = jax.random.normal(key, s, _dt(dtype))
    ext = n.ndim - mu.ndim
    return mu.reshape(mu.shape + (1,) * ext) + n * sigma.reshape(sigma.shape + (1,) * ext)


@register("_sample_multinomial", aliases=("sample_multinomial",), needs_rng=True)
def sample_multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    n = 1
    for s in tuple(shape):
        n *= s
    logits = jnp.log(jnp.maximum(data, 1e-37))
    idx = jax.random.categorical(key, logits, axis=-1,
                                 shape=(n,) + data.shape[:-1])
    idx = jnp.moveaxis(idx, 0, -1)
    out_shape = data.shape[:-1] + tuple(shape) if shape else data.shape[:-1]
    idx = idx.reshape(out_shape).astype(_dt(dtype))
    if get_prob:
        logp = jnp.log(jnp.maximum(data, 1e-37))
        picked = jnp.take_along_axis(
            logp.reshape((-1, logp.shape[-1])),
            idx.reshape((logp.shape[:-1] and -1 or 1, -1)).astype(jnp.int32).reshape(-1, n if shape else 1),
            axis=-1,
        ).reshape(out_shape)
        return idx, picked
    return idx


@register("_sample_gamma", aliases=("sample_gamma",), needs_rng=True)
def sample_gamma(key, alpha, beta, shape=(), dtype="float32"):
    s = tuple(alpha.shape) + tuple(shape)
    ext = len(s) - alpha.ndim
    g = jax.random.gamma(key, _bcast(alpha, ext), s, _dt(dtype))
    return g * _bcast(beta, ext)


@register("_sample_exponential", aliases=("sample_exponential",),
          needs_rng=True)
def sample_exponential(key, lam, shape=(), dtype="float32"):
    s = tuple(lam.shape) + tuple(shape)
    e = jax.random.exponential(key, s, _dt(dtype))
    return e / _bcast(lam, len(s) - lam.ndim)


@register("_sample_poisson", aliases=("sample_poisson",), needs_rng=True)
def sample_poisson(key, lam, shape=(), dtype="float32"):
    s = tuple(lam.shape) + tuple(shape)
    return jax.random.poisson(
        key, _bcast(lam, len(s) - lam.ndim), s).astype(_dt(dtype))


@register("_sample_negative_binomial", aliases=("sample_negative_binomial",),
          needs_rng=True)
def sample_negative_binomial(key, k, p, shape=(), dtype="float32"):
    s = tuple(k.shape) + tuple(shape)
    ext = len(s) - k.ndim
    kg, kp = jax.random.split(key)
    kb, pb = _bcast(k, ext), _bcast(p, ext)
    lam = jax.random.gamma(kg, kb, s) * ((1 - pb) / pb)
    return jax.random.poisson(kp, lam, s).astype(_dt(dtype))


@register("_sample_generalized_negative_binomial",
          aliases=("sample_generalized_negative_binomial",), needs_rng=True)
def sample_gnb(key, mu, alpha, shape=(), dtype="float32"):
    s = tuple(mu.shape) + tuple(shape)
    ext = len(s) - mu.ndim
    kg, kp = jax.random.split(key)
    mub, ab = _bcast(mu, ext), _bcast(alpha, ext)
    safe_a = jnp.maximum(ab, 1e-12)
    lam = jax.random.gamma(kg, 1.0 / safe_a, s) * (safe_a * mub)
    lam = jnp.where(ab == 0, jnp.broadcast_to(mub, s), lam)
    return jax.random.poisson(kp, lam, s).astype(_dt(dtype))


@register("_histogram", aliases=("histogram",), num_outputs=2,
          optional_arrays=("bins",))
def _histogram(data, bins=None, bin_cnt=None, range=None):
    """Histogram counts (ref: src/operator/tensor/histogram.cc).

    Either ``bins`` is an array of monotonic bin edges, or ``bin_cnt`` +
    ``range=(lo, hi)`` define uniform bins. Returns (counts, edges)."""
    flat = data.reshape(-1)
    if bins is not None:
        edges = bins
        # searchsorted: index of the bin each value falls in
        idx = jnp.searchsorted(edges, flat, side="right") - 1
        nbins = edges.shape[0] - 1
        # right edge of the last bin is inclusive (numpy semantics)
        idx = jnp.where(flat == edges[-1], nbins - 1, idx)
        valid = (idx >= 0) & (idx < nbins)
    else:
        lo, hi = float(range[0]), float(range[1])
        if lo == hi:  # numpy's degenerate-range expansion
            lo, hi = lo - 0.5, hi + 0.5
        nbins = int(bin_cnt)
        width = (hi - lo) / nbins
        idx = jnp.floor((flat - lo) / width).astype(jnp.int32)
        idx = jnp.where(flat == hi, nbins - 1, idx)
        valid = (flat >= lo) & (flat <= hi)
        edges = lo + width * jnp.arange(nbins + 1, dtype=jnp.float32)
    counts = jnp.zeros((nbins,), jnp.int32)
    counts = counts.at[jnp.where(valid, idx, 0)].add(
        valid.astype(jnp.int32))
    return counts, edges


@register("_shuffle", aliases=("shuffle",), needs_rng=True)
def shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian", needs_rng=True)
def sample_unique_zipfian(key, range_max=1, shape=()):
    """Unique log-uniform (zipfian) candidate samples (ref:
    src/operator/random/unique_sample_op.cc — samples WITHOUT
    replacement; used by sampled-softmax candidate sampling).

    Sampling without replacement = Gumbel top-k over the class
    log-probs p(k) ∝ log(1 + 1/(k+1)): exact, one XLA top-k, no
    rejection loop. For very large ranges (> 2^21 classes) the densely
    materialized log-prob vector would dominate memory, so the sampler
    falls back to the plain log-uniform draw (may repeat — the regime
    where collisions are vanishingly rare anyway)."""
    n = 1
    for s in tuple(shape):
        n *= s
    rm = int(range_max)
    if n > rm:
        raise MXNetError(
            f"_sample_unique_zipfian: cannot draw {n} unique samples "
            f"from range_max={rm} classes")
    if rm <= (1 << 21):
        k = jnp.arange(rm, dtype=jnp.float32)
        logp = jnp.log(jnp.log1p(1.0 / (k + 1.0)))
        g = jax.random.gumbel(key, (rm,))
        _, out = jax.lax.top_k(logp + g, n)
        out = out.astype(jnp.int32)
    else:
        # approximate fallback: reference formula
        # floor(exp(u * log(range_max + 1))) - 1 in [0, rm) — class 0
        # (the most probable) included. Duplicates ARE likely here for
        # head classes (P(class 0) ~ log2/log(rm)); this regime trades
        # the without-replacement guarantee for not materializing an
        # rm-sized logit vector.
        u = jax.random.uniform(key, (n,))
        out = jnp.clip(
            jnp.exp(u * jnp.log(float(rm) + 1.0)).astype(jnp.int32) - 1,
            0, rm - 1)
    # second output: the reference reports rejection-loop trial counts;
    # the Gumbel-top-k path has no rejection loop, so this stays 1 per
    # sample — callers needing the reference's P(hit)=1-(1-p)^tries
    # correction should compute inclusion probabilities directly
    cnt = jnp.ones((n,), dtype=jnp.float32)
    return out.reshape(tuple(shape)), cnt
