"""Control flow operators — _foreach / _while_loop / _cond.

Mirrors src/operator/control_flow.cc (:63 _foreach, :526 _while_loop,
:899 _cond), where loop bodies are sub-symbols run via LoopState/CachedOp.
Here the body is a Python callable over arrays and the op lowers directly
onto XLA's structured control flow: ``lax.scan`` (foreach),
``lax.scan`` with an active-mask (while_loop — static trip count
``max_iterations`` keeps shapes static for the MXU; this is the standard
XLA formulation of a bounded while), and ``lax.cond``.

All three are differentiable through jax's autodiff of the structured
primitives; the nd-layer wrappers in ``ndarray.contrib`` record them on
the autograd tape as single closures.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("_foreach", wrap_jit=False)
def _foreach(*arrays, body=None, num_data=1, num_outputs=1):
    """Scan ``body`` over the leading axis of the data arrays.

    arrays = data_0..data_{num_data-1}, state_0..state_{S-1};
    body(xs: tuple, states: list) -> (outputs: list, new_states: list).
    Returns stacked per-step outputs followed by final states.
    """
    data = arrays[:num_data]
    states = list(arrays[num_data:])

    def step(carry, xs):
        outs, new_states = body(xs, list(carry))
        return tuple(new_states), tuple(outs)

    final_states, stacked = lax.scan(step, tuple(states), tuple(data))
    return tuple(stacked) + tuple(final_states)


@register("_while_loop", wrap_jit=False)
def _while_loop(*arrays, cond=None, func=None, max_iterations=None,
                num_outputs=1):
    """Bounded while: run up to ``max_iterations`` steps of ``func`` while
    ``cond(*loop_vars)`` holds.

    cond(states) -> scalar bool; func(states) -> (outputs, new_states).
    Per-step outputs are stacked into [max_iterations, ...] arrays; steps
    after the predicate fails keep the padding (zeros), matching the
    reference's fixed-extent symbolic while (control_flow.cc:526 — the
    graph executor also allocates max_iterations extents). Also returns
    the final states and the number of executed steps.
    """
    if max_iterations is None:
        raise ValueError("_while_loop requires max_iterations (static "
                         "shapes on TPU)")
    states = tuple(arrays)

    def step(carry, _):
        st, active, n = carry
        outs, new_st = func(list(st))
        ok = jnp.logical_and(
            active, jnp.asarray(cond(list(st))).astype(bool).reshape(()))
        merged = tuple(jnp.where(ok, n_, s_) for n_, s_ in zip(new_st, st))
        outs = tuple(jnp.where(ok, o, jnp.zeros_like(o)) for o in outs)
        return (merged, ok, n + ok.astype(jnp.int32)), outs

    (final_states, _active, n_steps), stacked = lax.scan(
        step, (states, jnp.asarray(True), jnp.asarray(0, jnp.int32)),
        None, length=int(max_iterations))
    return tuple(stacked) + tuple(final_states) + (n_steps,)


@register("_cond", wrap_jit=False)
def _cond(*arrays, pred=None, then_func=None, else_func=None, num_outputs=1):
    """lax.cond over the branch callables; both branches must produce
    outputs of identical shape/dtype (XLA requirement — the reference
    checks the same, control_flow.cc:899)."""
    inputs = tuple(arrays)
    p = jnp.asarray(pred(list(inputs))).astype(bool) if callable(pred) \
        else jnp.asarray(arrays[0]).astype(bool).reshape(())

    def then_branch(ins):
        out = then_func(list(ins))
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    def else_branch(ins):
        out = else_func(list(ins))
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    outs = lax.cond(p, then_branch, else_branch, inputs)
    return outs if len(outs) > 1 else outs[0]
