"""Detection + spatial sampling operators.

Mirrors src/operator/contrib/{multibox_prior,multibox_target,
multibox_detection,proposal,multi_proposal,psroi_pooling,
deformable_convolution}.cc and src/operator/{spatial_transformer,
grid_generator,bilinear_sampler}.cc.

TPU formulation notes: everything is static-shape. Greedy bipartite
anchor matching runs as a bounded fori_loop of argmax rounds over the
IoU matrix (identical semantics to the reference's while-loop, bounded
by the gt count); NMS keeps the candidate set and masks; Proposal
returns exactly rpn_post_nms_top_n rois per image (short lists pad by
repeating the best roi, the reference pads likewise).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ---------------------------------------------------------------------------
# shared bilinear sampling (absolute pixel coordinates)
# ---------------------------------------------------------------------------

def _bilinear_gather(img, ys, xs):
    """img (C, H, W); ys/xs arbitrary equal shapes of float pixel coords.
    Out-of-range samples contribute 0 (the reference's border handling
    for bilinear_sampler/deformable conv)."""
    C, H, W = img.shape
    y0f = jnp.floor(ys)
    x0f = jnp.floor(xs)
    ly = ys - y0f
    lx = xs - x0f

    def tap(yi, xi, w):
        inb = (yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = img[:, yc, xc]
        return v * (w * inb.astype(img.dtype))

    return (tap(y0f, x0f, (1 - ly) * (1 - lx))
            + tap(y0f, x0f + 1, (1 - ly) * lx)
            + tap(y0f + 1, x0f, ly * (1 - lx))
            + tap(y0f + 1, x0f + 1, ly * lx))


# ---------------------------------------------------------------------------
# MultiBox family (SSD)
# ---------------------------------------------------------------------------

@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes per feature-map pixel
    (ref: contrib/multibox_prior.cc MultiBoxPriorForward)."""
    H, W = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1)  # (H, W, 2)

    whs = []
    for s in sizes:  # ratio = 1, all sizes
        whs.append((s * H / W / 2.0, s / 2.0))
    for r in ratios[1:]:  # size = sizes[0], remaining ratios
        sr = float(r) ** 0.5
        whs.append((sizes[0] * H / W * sr / 2.0, sizes[0] / sr / 2.0))
    wh = jnp.asarray(whs, jnp.float32)  # (A, 2) half-extents (w, h)

    ctr = cyx[:, :, None, :]  # (H, W, 1, 2) as (y, x)
    xmin = ctr[..., 1] - wh[None, None, :, 0]
    ymin = ctr[..., 0] - wh[None, None, :, 1]
    xmax = ctr[..., 1] + wh[None, None, :, 0]
    ymax = ctr[..., 0] + wh[None, None, :, 1]
    out = jnp.stack([xmin, ymin, xmax, ymax], -1).reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _iou_matrix(anchors, gt):
    """anchors (N, 4), gt (M, 4) corner boxes -> (N, M) IoU."""
    tl = jnp.maximum(anchors[:, None, :2], gt[None, :, :2])
    br = jnp.minimum(anchors[:, None, 2:4], gt[None, :, 2:4])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    a = ((anchors[:, 2] - anchors[:, 0])
         * (anchors[:, 3] - anchors[:, 1]))[:, None]
    b = ((gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1]))[None, :]
    union = a + b - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",))
def multibox_target(anchors, labels, cls_preds, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5,
                    minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Assign anchors to ground truths
    (ref: contrib/multibox_target.cc MultiBoxTargetForward).

    anchors (1, N, 4); labels (B, M, 5) rows [cls, x1, y1, x2, y2] with
    cls=-1 padding; cls_preds (B, num_classes, N).
    Returns loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N).
    """
    anc = anchors.reshape(-1, 4)
    N = anc.shape[0]
    M = labels.shape[1]
    vx, vy, vw, vh = variances

    def one(lab, cls_pred):
        valid_gt = lab[:, 0] >= 0  # (M,)
        iou = _iou_matrix(anc, lab[:, 1:5])  # (N, M)
        iou = jnp.where(valid_gt[None, :], iou, -1.0)

        # stage 1: greedy bipartite matching — each round picks the
        # globally best still-unmatched (anchor, gt) pair
        def round_fn(_, st):
            match, amask, gmask = st
            m = jnp.where(amask[:, None] & gmask[None, :], iou, -1.0)
            flat = jnp.argmax(m)
            bi, bk = flat // M, flat % M
            ok = m[bi, bk] > 1e-6
            match = jnp.where(ok, match.at[bi].set(bk), match)
            amask = jnp.where(ok, amask.at[bi].set(False), amask)
            gmask = jnp.where(ok, gmask.at[bk].set(False), gmask)
            return match, amask, gmask

        match0 = jnp.full((N,), -1, jnp.int32)
        match, amask, _ = lax.fori_loop(
            0, M, round_fn,
            (match0, jnp.ones((N,), bool), jnp.ones((M,), bool)))

        # stage 2: remaining anchors match their best gt above threshold
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        best_iou = jnp.max(iou, axis=1)
        thr_ok = amask & (best_iou > overlap_threshold) \
            & (overlap_threshold > 0)
        match = jnp.where(thr_ok, best_gt, match)

        pos = match >= 0
        mg = jnp.clip(match, 0, M - 1)
        gt = lab[mg]  # (N, 5)

        # location targets in variance-normalized center form
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        ax = (anc[:, 0] + anc[:, 2]) / 2
        ay = (anc[:, 1] + anc[:, 3]) / 2
        gw = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
        gh = jnp.maximum(gt[:, 4] - gt[:, 2], 1e-12)
        gx = (gt[:, 1] + gt[:, 3]) / 2
        gy = (gt[:, 2] + gt[:, 4]) / 2
        tx = (gx - ax) / jnp.maximum(aw, 1e-12) / vx
        ty = (gy - ay) / jnp.maximum(ah, 1e-12) / vy
        tw = jnp.log(gw / jnp.maximum(aw, 1e-12)) / vw
        th = jnp.log(gh / jnp.maximum(ah, 1e-12)) / vh
        loc_t = jnp.stack([tx, ty, tw, th], -1) * pos[:, None]
        loc_m = jnp.tile(pos[:, None], (1, 4)).astype(jnp.float32)

        cls_t = jnp.where(pos, gt[:, 0].astype(jnp.int32) + 1, 0)
        if negative_mining_ratio > 0:
            # hard negative mining: keep the highest-scoring negatives
            # (max non-background prob), rest become ignore_label
            neg_ok = (~pos) & (best_iou < negative_mining_thresh)
            max_p = jnp.max(cls_pred[1:], axis=0)  # skip background row
            order = jnp.argsort(-jnp.where(neg_ok, max_p, -jnp.inf))
            rank = jnp.zeros((N,), jnp.int32).at[order].set(
                jnp.arange(N, dtype=jnp.int32))
            n_pos = jnp.sum(pos.astype(jnp.int32))
            n_neg = jnp.minimum(
                jnp.maximum(
                    (n_pos * negative_mining_ratio).astype(jnp.int32),
                    int(minimum_negative_samples)),
                N - n_pos)
            keep_neg = neg_ok & (rank < n_neg)
            cls_t = jnp.where(pos, cls_t,
                              jnp.where(keep_neg, 0,
                                        jnp.int32(ignore_label)))
        return loc_t.reshape(-1), loc_m.reshape(-1), \
            cls_t.astype(jnp.float32)

    loc_t, loc_m, cls_t = jax.vmap(one)(labels, cls_preds)
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",))
def multibox_detection(cls_prob, loc_pred, anchors, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions into detections + NMS
    (ref: contrib/multibox_detection.cc). Output (B, N, 6) rows
    [cls_id, score, x1, y1, x2, y2]; pruned entries have cls_id=-1."""
    from .contrib import box_nms

    anc = anchors.reshape(-1, 4)
    N = anc.shape[0]
    vx, vy, vw, vh = variances
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]
    ax = (anc[:, 0] + anc[:, 2]) / 2
    ay = (anc[:, 1] + anc[:, 3]) / 2

    def one(cp, lp):
        # cp (num_classes, N), lp (N*4,)
        p = lp.reshape(N, 4)
        ox = p[:, 0] * vx * aw + ax
        oy = p[:, 1] * vy * ah + ay
        ow = jnp.exp(p[:, 2] * vw) * aw / 2
        oh = jnp.exp(p[:, 3] * vh) * ah / 2
        boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        masked = cp.at[background_id].set(-jnp.inf) \
            if 0 <= background_id < cp.shape[0] else cp
        cls = jnp.argmax(masked, axis=0)
        score = jnp.max(masked, axis=0)
        # class ids shift down past background (reference convention)
        out_id = jnp.where(cls > background_id, cls - 1, cls) \
            .astype(jnp.float32)
        keep = score > threshold
        out_id = jnp.where(keep, out_id, -1.0)
        score = jnp.where(keep, score, -1.0)
        return jnp.concatenate(
            [out_id[:, None], score[:, None], boxes], -1)

    dets = jax.vmap(one)(cls_prob, loc_pred.reshape(cls_prob.shape[0], -1))
    out = box_nms(dets, overlap_thresh=nms_threshold, valid_thresh=0.0,
                  topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                  background_id=-1, force_suppress=force_suppress)
    # reference marks suppressed rows by cls_id = -1
    # (multibox_detection-inl.h NMS loop)
    return out.at[..., 0].set(
        jnp.where(out[..., 1] < 0, -1.0, out[..., 0]))


# ---------------------------------------------------------------------------
# Proposal (Faster-RCNN RPN)
# ---------------------------------------------------------------------------

def _mkanchors(base_size, scales, ratios):
    """Base anchors centered at (base/2-0.5, ...) like the reference's
    GenerateAnchors (contrib/proposal-inl.h)."""
    import numpy as np
    base = np.array([0, 0, base_size - 1, base_size - 1], np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    out = []
    size = w * h
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            out.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                        cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.array(out, np.float32)


@register("_contrib_Proposal", aliases=("Proposal", "_contrib_MultiProposal",
                                        "MultiProposal"))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal generation (ref: contrib/proposal.cc /
    multi_proposal.cc — one kernel serves both; this version is batched
    over images like MultiProposal). Output rois (B*post_nms, 5) rows
    [batch_idx, x1, y1, x2, y2] (+ scores when output_score)."""
    B, A2, H, W = cls_prob.shape
    A = A2 // 2
    base = jnp.asarray(_mkanchors(feature_stride, list(scales),
                                  list(ratios)))  # (A, 4)
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    shift = jnp.stack(
        [jnp.tile(sx[None, :], (H, 1)), jnp.tile(sy[:, None], (1, W)),
         jnp.tile(sx[None, :], (H, 1)), jnp.tile(sy[:, None], (1, W))],
        -1)  # (H, W, 4)
    anchors = (shift[:, :, None, :] + base[None, None]).reshape(-1, 4)
    K = anchors.shape[0]  # H*W*A

    def one(cp, bp, info):
        # scores: foreground half of cls_prob, layout (A..., H, W)
        score = cp[A:].transpose(1, 2, 0).reshape(-1)  # (H*W*A,)
        deltas = bp.reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        ah = anchors[:, 3] - anchors[:, 1] + 1
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ax = anchors[:, 0] + 0.5 * (aw - 1)
        ay = anchors[:, 1] + 0.5 * (ah - 1)
        cx = deltas[:, 0] * aw + ax
        cy = deltas[:, 1] * ah + ay
        pw = jnp.exp(deltas[:, 2]) * aw
        ph = jnp.exp(deltas[:, 3]) * ah
        boxes = jnp.stack([cx - 0.5 * (pw - 1), cy - 0.5 * (ph - 1),
                           cx + 0.5 * (pw - 1), cy + 0.5 * (ph - 1)], -1)
        boxes = jnp.stack(
            [jnp.clip(boxes[:, 0], 0, info[1] - 1),
             jnp.clip(boxes[:, 1], 0, info[0] - 1),
             jnp.clip(boxes[:, 2], 0, info[1] - 1),
             jnp.clip(boxes[:, 3], 0, info[0] - 1)], -1)
        ms = rpn_min_size * info[2]
        keep = ((boxes[:, 2] - boxes[:, 0] + 1) >= ms) \
            & ((boxes[:, 3] - boxes[:, 1] + 1) >= ms)
        score_k = jnp.where(keep, score, -1.0)

        pre = min(int(rpn_pre_nms_top_n), K) if rpn_pre_nms_top_n > 0 else K
        order = jnp.argsort(-score_k)[:pre]
        b = boxes[order]
        s = score_k[order]

        # masked greedy NMS over the pre-nms list
        tl = jnp.maximum(b[:, None, :2], b[None, :, :2])
        br = jnp.minimum(b[:, None, 2:4], b[None, :, 2:4])
        wh = jnp.maximum(br - tl + 1, 0)
        inter = wh[..., 0] * wh[..., 1]
        area = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                  1e-12)

        def body(i, kp):
            live = kp[i] & (s[i] > -1)
            sup = (iou[i] > threshold) & (jnp.arange(pre) > i) & live
            return jnp.where(sup, False, kp)

        kp = lax.fori_loop(0, pre, body, jnp.ones((pre,), bool))
        kp &= s > -1
        # stable-compact the kept rois to the front, pad with roi 0
        # (the reference pads short lists by repeating proposals)
        rank = jnp.cumsum(kp.astype(jnp.int32)) - 1
        post = int(rpn_post_nms_top_n)
        tgt = jnp.where(kp & (rank < post), rank, post)  # post = dropped
        out_b = jnp.zeros((post + 1, 4), b.dtype).at[tgt].set(b)[:post]
        out_s = jnp.zeros((post + 1,), s.dtype).at[tgt].set(s)[:post]
        n_kept = jnp.minimum(jnp.sum(kp.astype(jnp.int32)), post)
        idx = jnp.arange(post)
        fill = jnp.maximum(n_kept, 1)
        out_b = jnp.where((idx < n_kept)[:, None], out_b,
                          out_b[idx % fill])
        out_s = jnp.where(idx < n_kept, out_s, out_s[idx % fill])
        return out_b, out_s

    ob, os_ = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    bidx = jnp.repeat(jnp.arange(B, dtype=cls_prob.dtype),
                      int(rpn_post_nms_top_n))
    rois = jnp.concatenate([bidx[:, None], ob.reshape(-1, 4)], -1)
    if output_score:
        return rois, os_.reshape(-1, 1)
    return rois


# ---------------------------------------------------------------------------
# PSROIPooling / DeformableConvolution (R-FCN family)
# ---------------------------------------------------------------------------

@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                  pooled_size=7, group_size=0):
    """Position-sensitive ROI pooling
    (ref: contrib/psroi_pooling.cc): input channel o*ps*ps + py*ps + px
    feeds output channel o at bin (py, px); average over bin pixels."""
    ps = int(pooled_size)
    gs = int(group_size) or ps
    N, C, H, W = data.shape

    def one(roi):
        bidx = jnp.clip(roi[0].astype(jnp.int32), 0, N - 1)
        img = data[bidx]
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = jnp.round(roi[3] + 1.0) * spatial_scale
        y2 = jnp.round(roi[4] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1) / ps
        rw = jnp.maximum(x2 - x1, 0.1) / ps
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        out = jnp.zeros((output_dim, ps, ps), data.dtype)
        for py in range(ps):
            for px in range(ps):
                hs = jnp.floor(y1 + py * rh)
                he = jnp.ceil(y1 + (py + 1) * rh)
                wss = jnp.floor(x1 + px * rw)
                we = jnp.ceil(x1 + (px + 1) * rw)
                m = ((ys >= hs) & (ys < he))[:, None] \
                    & ((xs >= wss) & (xs < we))[None, :]
                cnt = jnp.maximum(jnp.sum(m), 1)
                gy = min(py * gs // ps, gs - 1)
                gx = min(px * gs // ps, gs - 1)
                chans = (jnp.arange(output_dim) * gs + gy) * gs + gx
                v = jnp.sum(jnp.where(m[None], img[chans], 0.0),
                            axis=(1, 2)) / cnt
                out = out.at[:, py, px].set(v)
        return out

    return jax.vmap(one)(rois)


@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=0, num_group=1,
                           num_deformable_group=1, no_bias=False):
    """Deformable conv v1 (ref: contrib/deformable_convolution.cc):
    each kernel tap samples the input at its position + a learned
    per-location offset via bilinear interpolation, then an ordinary
    conv accumulates the sampled values (expressed as an einsum so the
    MXU still does the contraction)."""
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    N, C, H, W = data.shape
    OH = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    OW = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    G = int(num_deformable_group)

    oy = jnp.arange(OH, dtype=jnp.float32) * sh - ph
    ox = jnp.arange(OW, dtype=jnp.float32) * sw - pw
    base_y = oy[:, None, None] + jnp.arange(kh, dtype=jnp.float32)[None, :, None] * dh
    base_x = ox[:, None, None] + jnp.arange(kw, dtype=jnp.float32)[None, None, :] * dw
    # base_y (OH, kh, 1), base_x (OW, 1, kw)

    def one(img, off):
        # off (2*G*kh*kw, OH, OW) layout [g, kh, kw, {y,x}] per reference
        off = off.reshape(G, kh, kw, 2, OH, OW)

        def sample_group(img_g, off_g):
            # build (OH, OW, kh, kw) sampling grids
            yy = (base_y[:, None, :, :]  # (OH, 1, kh, 1)
                  + jnp.zeros((1, OW, 1, kw), jnp.float32))
            xx = (base_x[None, :, :, :].reshape(1, OW, 1, kw)
                  + jnp.zeros((OH, 1, kh, 1), jnp.float32))
            yy = yy + off_g[:, :, 0].transpose(2, 3, 0, 1)
            xx = xx + off_g[:, :, 1].transpose(2, 3, 0, 1)
            flat_y = yy.reshape(-1)
            flat_x = xx.reshape(-1)
            v = _bilinear_gather(img_g, flat_y, flat_x)
            return v.reshape(img_g.shape[0], OH, OW, kh, kw)

        cpg = C // G
        cols = []
        for g in range(G):
            # off_g indexed [kh, kw, 2, OH, OW]
            off_g = off[g]
            cols.append(sample_group(img[g * cpg:(g + 1) * cpg], off_g))
        return jnp.concatenate(cols, axis=0)  # (C, OH, OW, kh, kw)

    sampled = jax.vmap(one)(data, offset)  # (N, C, OH, OW, kh, kw)
    O = weight.shape[0]
    cg = int(num_group)
    if cg == 1:
        out = jnp.einsum("nchwij,ocij->nohw", sampled, weight)
    else:
        outs = []
        opg, cpg = O // cg, C // cg
        for g in range(cg):
            outs.append(jnp.einsum(
                "nchwij,ocij->nohw",
                sampled[:, g * cpg:(g + 1) * cpg],
                weight[g * opg:(g + 1) * opg]))
        out = jnp.concatenate(outs, axis=1)
    if bias is not None and not no_bias:
        out = out + bias[None, :, None, None]
    return out


# ---------------------------------------------------------------------------
# SpatialTransformer family
# ---------------------------------------------------------------------------

@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Generate sampling grids (ref: src/operator/grid_generator-inl.h).

    affine: data (B, 6) -> grid (B, 2, H, W) of normalized (x, y) in
    [-1, 1]; warp: data (B, 2, H, W) flow field added to the identity
    grid and normalized."""
    if transform_type == "affine":
        H, W = target_shape
        B = data.shape[0]
        # endpoint convention: dst grid = linspace(-1, 1, n)
        # (ref: spatial_transformer-inl.h:99-101)
        ys = -1.0 + jnp.arange(H, dtype=jnp.float32) * 2.0 / max(H - 1, 1)
        xs = -1.0 + jnp.arange(W, dtype=jnp.float32) * 2.0 / max(W - 1, 1)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx, gy, ones], 0).reshape(3, -1)  # (3, H*W)
        theta = data.reshape(B, 2, 3)
        out = jnp.einsum("bij,jk->bik", theta, src)  # (B, 2, H*W)
        return out.reshape(B, 2, H, W)
    # warp: flow field (B, 2, H, W) in pixels
    B, _, H, W = data.shape
    xs = jnp.arange(W, dtype=jnp.float32)
    ys = jnp.arange(H, dtype=jnp.float32)
    gx = jnp.broadcast_to(xs[None, :], (H, W))
    gy = jnp.broadcast_to(ys[:, None], (H, W))
    fx = (data[:, 0] + gx[None])
    fy = (data[:, 1] + gy[None])
    nx = fx * 2 / jnp.maximum(W - 1, 1) - 1
    ny = fy * 2 / jnp.maximum(H - 1, 1) - 1
    return jnp.stack([nx, ny], 1)


@register("BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=None):
    """Sample data at normalized grid locations
    (ref: src/operator/bilinear_sampler.cc). grid (B, 2, H', W') holds
    (x, y) in [-1, 1]; out-of-range taps read 0."""
    B, C, H, W = data.shape
    xs = (grid[:, 0] + 1) * (W - 1) / 2
    ys = (grid[:, 1] + 1) * (H - 1) / 2

    def one(img, y, x):
        return _bilinear_gather(img, y.reshape(-1), x.reshape(-1)) \
            .reshape(C, *y.shape)

    return jax.vmap(one)(data, ys, xs)


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine",
                        sampler_type="bilinear", cudnn_off=None):
    """Affine spatial transformer = GridGenerator + BilinearSampler
    (ref: src/operator/spatial_transformer.cc)."""
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=tuple(target_shape))
    return bilinear_sampler(data, grid)
