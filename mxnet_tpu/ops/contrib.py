"""Contrib ops — detection, transformer helpers, misc.

Mirrors src/operator/contrib/. Detection ops (box_nms, MultiBox*, ROIAlign,
Proposal) are the data-dependent-shape hard cases flagged in SURVEY.md §7(c):
on TPU they are expressed with *bounded static shapes* — NMS keeps the full
candidate set and masks suppressed entries instead of compacting, which is the
standard XLA-friendly formulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.float32(data.shape[-1])).astype(data.dtype)


@register("_contrib_index_copy")
def index_copy(old, index, new_tensor):
    return old.at[index.astype(jnp.int32)].set(new_tensor)


@register("_contrib_quadratic", aliases=("quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    # the reference's tutorial op (src/operator/contrib/quadratic_op.cc)
    return a * data * data + b * data + c


def _box_iou_corner(b1, b2):
    """IoU between (..., N, 4) and (..., M, 4) corner boxes."""
    tl = jnp.maximum(b1[..., :, None, :2], b2[..., None, :, :2])
    br = jnp.minimum(b1[..., :, None, 2:4], b2[..., None, :, 2:4])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    a1 = jnp.maximum(b1[..., 2] - b1[..., 0], 0) * jnp.maximum(b1[..., 3] - b1[..., 1], 0)
    a2 = jnp.maximum(b2[..., 2] - b2[..., 0], 0) * jnp.maximum(b2[..., 3] - b2[..., 1], 0)
    return inter / jnp.maximum(a1[..., :, None] + a2[..., None, :] - inter, 1e-12)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner"):
    if format == "center":
        def c2c(b):
            xy = b[..., :2]
            wh = b[..., 2:4] / 2
            return jnp.concatenate([xy - wh, xy + wh], axis=-1)
        lhs, rhs = c2c(lhs), c2c(rhs)
    return _box_iou_corner(lhs, rhs)


@register("_contrib_box_nms", aliases=("box_nms", "_contrib_nms"), wrap_jit=True)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Static-shape NMS: output has the input's shape; suppressed boxes get
    score -1 (the reference's convention for pruned entries)."""

    def one(batch):
        scores = batch[:, score_index]
        boxes = batch[:, coord_start:coord_start + 4]
        if in_format == "center":
            xy, wh = boxes[:, :2], boxes[:, 2:4] / 2
            boxes = jnp.concatenate([xy - wh, xy + wh], axis=-1)
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= batch[:, id_index] != background_id
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        sboxes = boxes[order]
        sscores = scores[order]
        svalid = valid[order]
        n = sboxes.shape[0]
        if topk > 0:
            # reference semantics: only the topk highest-scoring candidates
            # participate in suppression at all (bounding_box-inl.h)
            svalid &= jnp.arange(n) < topk
        iou = _box_iou_corner(sboxes, sboxes)
        if not force_suppress and id_index >= 0:
            ids = batch[order, id_index]
            same = ids[:, None] == ids[None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            live = keep[i] & svalid[i]
            sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i) & live
            return jnp.where(sup, False, keep)

        keep = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        keep &= svalid
        out = batch[order]
        out = out.at[:, score_index].set(jnp.where(keep, sscores, -1.0))
        return out

    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])
    out = jax.vmap(one)(flat)
    return out.reshape(shape)


@register("ROIPooling")
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    ph, pw = pooled_size
    N, C, H, W = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (jnp.round(roi[1:5] * spatial_scale)).astype(jnp.int32)
        img = data[jnp.clip(bidx, 0, N - 1)]
        h = jnp.maximum(y2 - y1 + 1, 1)
        w = jnp.maximum(x2 - x1 + 1, 1)
        ys = jnp.arange(H)
        xs = jnp.arange(W)
        out = jnp.full((C, ph, pw), -jnp.inf, data.dtype)
        for py in range(ph):
            for px in range(pw):
                ys0 = y1 + (py * h) // ph
                ys1 = y1 + ((py + 1) * h + ph - 1) // ph
                xs0 = x1 + (px * w) // pw
                xs1 = x1 + ((px + 1) * w + pw - 1) // pw
                m = ((ys >= ys0) & (ys < jnp.maximum(ys1, ys0 + 1)))[:, None] & \
                    ((xs >= xs0) & (xs < jnp.maximum(xs1, xs0 + 1)))[None, :]
                v = jnp.max(jnp.where(m[None], img, -jnp.inf), axis=(1, 2))
                out = out.at[:, py, px].set(v)
        return out

    return jax.vmap(one_roi)(rois)


@register("_contrib_ROIAlign")
def roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False):
    ph, pw = pooled_size
    N, C, H, W = data.shape
    sr = max(int(sample_ratio), 1)

    def bilinear(img, y, x):
        y0 = jnp.clip(jnp.floor(y), 0, H - 1)
        x0 = jnp.clip(jnp.floor(x), 0, W - 1)
        y1 = jnp.clip(y0 + 1, 0, H - 1)
        x1 = jnp.clip(x0 + 1, 0, W - 1)
        ly, lx = y - y0, x - x0
        y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in (y0, x0, y1, x1))
        v = (img[:, y0i, x0i] * (1 - ly) * (1 - lx)
             + img[:, y0i, x1i] * (1 - ly) * lx
             + img[:, y1i, x0i] * ly * (1 - lx)
             + img[:, y1i, x1i] * ly * lx)
        return v

    off = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = jnp.clip(roi[0].astype(jnp.int32), 0, N - 1)
        img = data[bidx]
        x1, y1, x2, y2 = roi[1] * spatial_scale - off, roi[2] * spatial_scale - off, \
            roi[3] * spatial_scale - off, roi[4] * spatial_scale - off
        rh = jnp.maximum(y2 - y1, 1e-6) / ph
        rw = jnp.maximum(x2 - x1, 1e-6) / pw
        py = jnp.arange(ph)[:, None, None, None]
        px = jnp.arange(pw)[None, :, None, None]
        iy = jnp.arange(sr)[None, None, :, None]
        ix = jnp.arange(sr)[None, None, None, :]
        # full (ph, pw, sr, sr) sample grid: y varies over (py, iy),
        # x over (px, ix) — broadcast BEFORE flattening, else the two
        # flattened axes pair up elementwise (diagonal sampling)
        ys = jnp.broadcast_to(y1 + (py + (iy + 0.5) / sr) * rh,
                              (ph, pw, sr, sr))
        xs = jnp.broadcast_to(x1 + (px + (ix + 0.5) / sr) * rw,
                              (ph, pw, sr, sr))
        vals = bilinear(img, ys.reshape(-1), xs.reshape(-1))
        vals = vals.reshape(C, ph, pw, sr * sr)
        return jnp.mean(vals, axis=-1)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# FFT (ref: src/operator/contrib/fft-inl.h, ifft-inl.h). The reference runs
# cuFFT C2C over the last axis with real input and interleaved re/im output;
# ifft is the UNNORMALIZED inverse (fft-inl.h's `out /= dim_` is commented
# out at ifft-inl.h:136). compute_size is a cuFFT batching knob — XLA batches
# natively, so it is accepted and ignored.
# ---------------------------------------------------------------------------


@register("_contrib_fft", aliases=("fft",))
def contrib_fft(data, compute_size=128):
    spec = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@register("_contrib_ifft", aliases=("ifft",))
def contrib_ifft(data, compute_size=128):
    d = data.shape[-1] // 2
    inter = data.astype(jnp.float32).reshape(data.shape[:-1] + (d, 2))
    spec = lax.complex(inter[..., 0], inter[..., 1])
    # unnormalized inverse: numpy's ifft divides by d, the reference does not
    return (jnp.fft.ifft(spec, axis=-1).real * d).astype(data.dtype)


@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch random projection (ref: src/operator/contrib/
    count_sketch-inl.h): out[i, h[j]] += s[j] * data[i, j]. A scatter-add
    over the hash indices; processing_batch_size is a CUDA grid-size knob,
    ignored under XLA."""
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    flat = data.reshape(-1, data.shape[-1])
    out = jnp.zeros((flat.shape[0], int(out_dim)), data.dtype)
    out = out.at[:, hh].add(flat * ss[None, :])
    return out.reshape(data.shape[:-1] + (int(out_dim),))


@register("_contrib_boolean_mask", aliases=("boolean_mask",), num_outputs=2)
def contrib_boolean_mask(data, index, axis=0):
    """Select rows where index != 0 (ref: src/operator/contrib/
    boolean_mask.cc). The reference produces a data-dependent output shape;
    under XLA the kept rows are compacted to the front of a full-size,
    zero-padded buffer and the true count is returned as a second output —
    the bounded-shape formulation SURVEY.md §7(c) prescribes."""
    ax = axis % data.ndim
    keep = (index.reshape(-1) != 0)
    n = data.shape[ax]
    # stable compaction: position of each kept row in the packed output
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    dest = jnp.where(keep, pos, n)  # dropped rows scatter to a discard slot
    moved = jnp.moveaxis(data, ax, 0)
    packed = jnp.zeros((n + 1,) + moved.shape[1:], data.dtype)
    packed = packed.at[dest].set(moved)[:n]
    return jnp.moveaxis(packed, 0, ax), jnp.sum(keep.astype(jnp.int32))


@register("_contrib_SyncBatchNorm", aliases=("SyncBatchNorm",))
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    output_mean_var=False, ndev=1, key="", axis_name=None,
                    training=False):
    """Cross-device BatchNorm (ref: src/operator/contrib/sync_batch_norm.cc).

    The reference synchronizes batch statistics across GPUs with a
    shared-buffer barrier keyed by `key`/`ndev`. TPU-natively the op is SPMD:
    when traced inside shard_map/pjit with a mapped `axis_name`, the batch
    moments are jointly reduced with lax.pmean over that axis — the mean of
    per-device means/second-moments IS the global moment since shards are
    equal-sized. Outside a mapped trace it degrades to plain BatchNorm.
    """
    reduce_axes = tuple(i for i in range(data.ndim) if i != 1)
    bshape = tuple(data.shape[1] if i == 1 else 1 for i in range(data.ndim))
    if training and not use_global_stats:
        mean = jnp.mean(data, axis=reduce_axes)
        sq = jnp.mean(jnp.square(data), axis=reduce_axes)
        if axis_name:
            mean = lax.pmean(mean, axis_name)
            sq = lax.pmean(sq, axis_name)
        var = sq - jnp.square(mean)
    else:
        mean, var = moving_mean, moving_var
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape)) * (inv * g).reshape(bshape) \
        + beta.reshape(bshape)
    if output_mean_var:
        return out, mean, var
    return out


@register("_contrib_flash_attention", aliases=("flash_attention",),
          wrap_jit=False)
def contrib_flash_attention(q, k, v, causal=False, scale=None):
    """Blockwise Pallas attention (O(T) memory) with automatic
    dense-path dispatch below the measured crossover — the TPU analogue
    of the reference's fused transformer helpers
    (src/operator/contrib/transformer.cc interleaved_matmul_*)."""
    from .pallas_kernels import flash_attention as _fa
    return _fa(q, k, v, causal=bool(causal), scale=scale)
