"""Op-name parity sweep: the remaining reference registrations
(VERDICT r3 #5) that had no counterpart name in this registry.

Grouped by reference source file; each op is a pure JAX lowering with
the reference's call signature. Gradient comes from jax.vjp as
everywhere else (the reference's `_backward_*` registrations are
therefore structural non-goals — see tools/op_parity.py EXCLUSIONS).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

# ---------------------------------------------------------------------------
# elemwise / unary (ref: src/operator/tensor/elemwise_*.cc)
# ---------------------------------------------------------------------------


@register("reshape_like")
def reshape_like(lhs, rhs):
    """ref: tensor/elemwise_unary_op_basic.cc reshape_like."""
    return lhs.reshape(rhs.shape)


@register("round")
def round_(data):
    """Round half away from zero (ref: mshadow_op::round — NOT banker's
    rounding, which jnp.round would give)."""
    return jnp.where(data >= 0, jnp.floor(data + 0.5), jnp.ceil(data - 0.5))


@register("hard_sigmoid")
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    """ref: tensor/elemwise_unary_op_basic.cc hard_sigmoid."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("softmin")
def softmin(data, axis=-1, temperature=None, dtype=None):
    """softmax over negated input (ref: nn/softmax.cc softmin)."""
    x = -data
    if temperature:
        x = x / temperature
    out = jax.nn.softmax(x, axis=axis)
    return out.astype(dtype) if dtype else out


def _as_bool_float(fn, name, doc):
    def op(lhs, rhs):
        return fn(lhs, rhs).astype(lhs.dtype)
    op.__name__ = name
    op.__doc__ = doc
    return op


for _n, _f in [
        ("_logical_and", lambda a, b: (a != 0) & (b != 0)),
        ("_logical_or", lambda a, b: (a != 0) | (b != 0)),
        ("_logical_xor", lambda a, b: (a != 0) ^ (b != 0)),
        ("_not_equal", lambda a, b: a != b),
        ("_greater", lambda a, b: a > b),
        ("_greater_equal", lambda a, b: a >= b),
        ("_lesser", lambda a, b: a < b),
        ("_lesser_equal", lambda a, b: a <= b)]:
    register(_n)(_as_bool_float(
        _f, _n, f"elemwise {_n} (ref: tensor/elemwise_binary_op_logic.cc)"))


@register("_mod")
def _mod(lhs, rhs):
    """C-style fmod semantics (ref: mshadow_op::mod — sign follows the
    dividend, unlike jnp.mod which follows the divisor)."""
    return jnp.fmod(lhs, rhs)


@register("_grad_add")
def _grad_add(lhs, rhs):
    """Gradient accumulation add (ref: elemwise_binary_op_basic.cc) —
    numerically identical to elemwise_add; registered separately because
    graph passes treat it as an always-accumulate edge."""
    return lhs + rhs


@register("broadcast_plus")
def broadcast_plus(lhs, rhs):
    """alias family of broadcast_add (ref: elemwise_binary_broadcast_op
    _basic.cc registers broadcast_plus separately, not as an alias)."""
    return lhs + rhs


@register("broadcast_minus")
def broadcast_minus(lhs, rhs):
    return lhs - rhs


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs; rhs only contributes shape/stype attrs during
    graph passes (ref: elemwise_unary_op_basic.cc)."""
    return lhs


@register("_zeros_without_dtype")
def _zeros_without_dtype(shape=(), ctx=None, dtype=None):
    """zeros whose dtype is inferred (defaults f32) — the reference
    registers this for the Gradient pass's zero-grad nodes."""
    return jnp.zeros(tuple(shape),
                     jnp.dtype(dtype) if dtype else jnp.float32)


@register("_rnn_param_concat", num_inputs=None)
def _rnn_param_concat(*args, dim=0, num_args=None):
    """Concat specialization for fused-RNN parameter packing
    (ref: rnn.cc _rnn_param_concat — same math as Concat, separate name
    so the storage planner can fold it)."""
    return jnp.concatenate(args, axis=dim)


# scatter_* — elemwise on sparse storage in the reference
# (elemwise_scatter_op.cc); with dense XLA buffers the math is identical,
# the names exist so sparse-aware callers resolve.
@register("_scatter_plus_scalar")
def _scatter_plus_scalar(data, scalar=0.0):
    return data + scalar


@register("_scatter_minus_scalar")
def _scatter_minus_scalar(data, scalar=0.0):
    return data - scalar


@register("_scatter_elemwise_div")
def _scatter_elemwise_div(lhs, rhs):
    return lhs / rhs


# ---------------------------------------------------------------------------
# index transforms (ref: src/operator/tensor/ravel.cc)
# ---------------------------------------------------------------------------


@register("_ravel_multi_index")
def _ravel_multi_index(data, shape=()):
    """data (ndim, N) coordinates -> (N,) flat indices."""
    coords = tuple(data[i].astype(jnp.int32) for i in range(len(shape)))
    out = jnp.ravel_multi_index(coords, tuple(int(s) for s in shape),
                                mode="clip")
    return out.astype(data.dtype)


@register("_unravel_index")
def _unravel_index(data, shape=()):
    """data (N,) flat indices -> (ndim, N) coordinates."""
    coords = jnp.unravel_index(data.astype(jnp.int32),
                               tuple(int(s) for s in shape))
    return jnp.stack(coords).astype(data.dtype)


# ---------------------------------------------------------------------------
# slice assignment (ref: tensor/matrix_op.cc _slice_assign — the op
# behind autograd-safe `x[a:b] = y`)
# ---------------------------------------------------------------------------


def _assign_slices(shape, begin, end, step):
    out = []
    step = tuple(step) or (None,) * len(begin)
    for i in range(len(begin)):
        st = step[i] if i < len(step) and step[i] not in (None, 0) else 1
        b, e = begin[i], end[i] if i < len(end) else None
        out.append(slice(b, e, st))
    return tuple(out)


@register("_slice_assign")
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    return lhs.at[_assign_slices(lhs.shape, begin, end, step)].set(rhs)


@register("_slice_assign_scalar")
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    return data.at[_assign_slices(data.shape, begin, end, step)].set(scalar)


# ---------------------------------------------------------------------------
# sparse-storage helpers, dense lowering (ref: tensor/cast_storage.cc,
# sparse_retain.cc, square_sum.cc) — the NDArray layer holds the actual
# CSR/row-sparse representations (ndarray/sparse.py); these registry ops
# give dense-semantics fallbacks under the reference names.
# ---------------------------------------------------------------------------


@register("cast_storage")
def cast_storage(data, stype=None):
    """Dense fallback is the identity; NDArray.tostype() performs real
    representation changes (ref: tensor/cast_storage.cc)."""
    return data


@register("_sparse_retain", num_outputs=1)
def _sparse_retain(data, indices):
    """Keep only the given rows, zero the rest (ref: sparse_retain.cc —
    defined on row_sparse; the dense lowering writes explicit zeros)."""
    idx = indices.astype(jnp.int32)
    mask = jnp.zeros((data.shape[0],), jnp.bool_).at[idx].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)),
                     data, jnp.zeros((), data.dtype))


@register("_square_sum")
def _square_sum(data, axis=None, keepdims=False, exclude=False):
    """sum(x^2) (ref: square_sum.cc — the fused kernel the row-sparse
    LAMB/adam paths use)."""
    from .tensor import _norm_axis
    ax = _norm_axis(axis, data.ndim, exclude)
    return jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims)


# ---------------------------------------------------------------------------
# image ops (ref: src/operator/image/image_random.cc)
# ---------------------------------------------------------------------------


@register("_image_to_tensor")
def _image_to_tensor(data):
    """HWC [0,255] -> CHW float32 [0,1]; batched NHWC -> NCHW."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize")
def _image_normalize(data, mean=(0.0,), std=(1.0,)):
    """Per-channel (x - mean) / std on CHW or NCHW float input."""
    c_axis = 0 if data.ndim == 3 else 1
    shape = tuple(-1 if i == c_axis else 1 for i in range(data.ndim))
    mean = jnp.asarray(mean, jnp.float32).reshape(shape)
    std = jnp.asarray(std, jnp.float32).reshape(shape)
    return (data - mean) / std


# ---------------------------------------------------------------------------
# contrib (ref: src/operator/contrib/)
# ---------------------------------------------------------------------------


@register("_contrib_SparseEmbedding")
def _contrib_sparse_embedding(data, weight, input_dim=0, output_dim=0,
                              dtype="float32", sparse_grad=True):
    """Embedding with row-sparse gradient storage in the reference
    (contrib/sparse_embedding... indexing math is Embedding's; the
    row-sparse gradient materializes through the optimizer's
    row-granular path here)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("_contrib_getnnz")
def _contrib_getnnz(data, axis=None):
    """Count non-zeros (ref: contrib/nnz.cc — defined on CSR; dense
    fallback counts directly)."""
    return jnp.sum((data != 0).astype(jnp.int32), axis=axis)


@register("_contrib_bipartite_matching", num_outputs=2)
def _contrib_bipartite_matching(data, is_ascend=False, threshold=1e-12,
                                topk=-1):
    """Greedy bipartite matching by score order (ref:
    contrib/bounding_box.cc:154, BipartiteMatchingForward): walk all
    (row, col) pairs from best score to worst; take a pair when both
    sides are free and the score passes `threshold`. Returns (rows,
    cols): per-row matched col index / per-col matched row index, -1
    when unmatched."""
    shape = data.shape
    n, m = shape[-2], shape[-1]
    flat = data.reshape(-1, n, m)

    def one(scores):
        order = jnp.argsort(scores.reshape(-1))
        if not is_ascend:
            order = order[::-1]
        limit = n * m if topk is None or topk < 0 else min(topk, n * m)

        def body(t, carry):
            rows, cols, taken = carry
            pos = order[t]
            i, j = pos // m, pos % m
            s = scores[i, j]
            ok = (rows[i] < 0) & (cols[j] < 0) & (taken < limit)
            ok &= (s <= threshold) if is_ascend else (s >= threshold)
            rows = rows.at[i].set(jnp.where(ok, j, rows[i]))
            cols = cols.at[j].set(jnp.where(ok, i, cols[j]))
            return rows, cols, taken + ok.astype(jnp.int32)

        rows0 = jnp.full((n,), -1, jnp.int32)
        cols0 = jnp.full((m,), -1, jnp.int32)
        rows, cols, _ = lax.fori_loop(0, n * m, body,
                                      (rows0, cols0, jnp.int32(0)))
        return rows.astype(data.dtype), cols.astype(data.dtype)

    rows, cols = jax.vmap(one)(flat)
    return (rows.reshape(shape[:-1]),
            cols.reshape(shape[:-2] + (m,)))


@register("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity forward; backward adds the KL-sparsity penalty gradient
    penalty * (-t/rho + (1-t)/(1-rho)) with rho the batch-mean
    activation (ref: identity_attach_KL_sparse_reg-inl.h — the
    reference keeps a momentum-smoothed rho in an aux state; the
    functional form uses the current batch's mean, which is the
    momentum=0 case)."""
    t, p = float(sparseness_target), float(penalty)

    @jax.custom_vjp
    def _f(x):
        return x

    def _fwd(x):
        return x, jnp.mean(x, axis=0)

    def _bwd(rho, g):
        kl_grad = p * (-t / rho + (1.0 - t) / (1.0 - rho))
        return (g + jnp.broadcast_to(kl_grad, g.shape),)

    _f.defvjp(_fwd, _bwd)
    return _f(data)


@register("_contrib_DeformablePSROIPooling", num_outputs=2,
          aliases=("DeformablePSROIPooling",))
def deformable_psroi_pooling(data, rois, trans, spatial_scale=1.0,
                             output_dim=1, group_size=1, pooled_size=7,
                             part_size=0, sample_per_part=1,
                             trans_std=0.0, no_trans=False):
    """Deformable position-sensitive ROI pooling (ref:
    contrib/deformable_psroi_pooling.cc, Dai 2017 §3.2): each output bin
    shifts by a learned normalized offset trans[(cls, {y,x}), part_y,
    part_x] * trans_std scaled by the ROI size, then averages
    sample_per_part^2 bilinear taps. Outputs (out, top_count) like the
    reference (top_count = live samples per bin)."""
    ps = int(pooled_size)
    gs = int(group_size) or ps
    pz = int(part_size) or ps
    sp = int(sample_per_part)
    N, C, H, W = data.shape

    ys_all = jnp.arange(H, dtype=jnp.float32)
    xs_all = jnp.arange(W, dtype=jnp.float32)

    def _bilinear(img, y, x):
        y0 = jnp.clip(jnp.floor(y), 0, H - 1)
        x0 = jnp.clip(jnp.floor(x), 0, W - 1)
        y1 = jnp.clip(y0 + 1, 0, H - 1)
        x1 = jnp.clip(x0 + 1, 0, W - 1)
        wy = y - y0
        wx = x - x0
        iy0, ix0, iy1, ix1 = (v.astype(jnp.int32) for v in (y0, x0, y1, x1))
        v = (img[:, iy0, ix0] * (1 - wy) * (1 - wx)
             + img[:, iy1, ix0] * wy * (1 - wx)
             + img[:, iy0, ix1] * (1 - wy) * wx
             + img[:, iy1, ix1] * wy * wx)
        return v

    def one(roi, tr):
        bidx = jnp.clip(roi[0].astype(jnp.int32), 0, N - 1)
        img = data[bidx]
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / ps
        bin_h = rh / ps
        sub_w = bin_w / sp
        sub_h = bin_h / sp
        out = jnp.zeros((output_dim, ps, ps), data.dtype)
        cnt = jnp.zeros((output_dim, ps, ps), data.dtype)
        for py in range(ps):
            for px in range(ps):
                part_y = py * pz // ps
                part_x = px * pz // ps
                if no_trans:
                    dy = dx = jnp.float32(0)
                else:
                    dy = tr[0, part_y, part_x] * trans_std * rh
                    dx = tr[1, part_y, part_x] * trans_std * rw
                gy = min(py * gs // ps, gs - 1)
                gx = min(px * gs // ps, gs - 1)
                chans = (jnp.arange(output_dim) * gs + gy) * gs + gx
                acc = jnp.zeros((output_dim,), jnp.float32)
                live = jnp.zeros((), jnp.float32)
                for iy in range(sp):
                    for ix in range(sp):
                        y = y1 + py * bin_h + dy + (iy + 0.5) * sub_h
                        x = x1 + px * bin_w + dx + (ix + 0.5) * sub_w
                        inb = (y > -1) & (y < H) & (x > -1) & (x < W)
                        yc = jnp.clip(y, 0, H - 1)
                        xc = jnp.clip(x, 0, W - 1)
                        v = _bilinear(img[chans], yc, xc)
                        acc = acc + jnp.where(inb, v, 0.0)
                        live = live + inb.astype(jnp.float32)
                out = out.at[:, py, px].set(
                    (acc / jnp.maximum(live, 1.0)).astype(data.dtype))
                cnt = cnt.at[:, py, px].set(live.astype(data.dtype))
        return out, cnt

    # trans: (num_rois or N, 2*num_classes, part, part); take the first
    # two channels per the no-class-aware default
    ntr = rois.shape[0]
    if no_trans:
        tr_all = jnp.zeros((ntr, 2, pz, pz), jnp.float32)
    else:
        tr_all = trans[:, :2].astype(jnp.float32)
    return jax.vmap(one)(rois, tr_all)
