"""Linear-algebra operator family (ref: src/operator/tensor/la_op.cc).

The reference shims cuBLAS/LAPACK (src/operator/c_lapack_api.h); here each op
is the corresponding jax.numpy / jax.scipy primitive, which XLA lowers to MXU
matmuls or host LAPACK as appropriate.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .registry import register


def _t(x, do):
    return jnp.swapaxes(x, -1, -2) if do else x


@register("_linalg_gemm", aliases=("linalg_gemm",))
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b)) + beta * C


@register("_linalg_gemm2", aliases=("linalg_gemm2",))
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))


@register("_linalg_potrf", aliases=("linalg_potrf",))
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", aliases=("linalg_potri",))
def linalg_potri(A):
    # inverse of X where A = potrf(X): inv = L^-T L^-1
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jsl.solve_triangular(A, eye, lower=True)
    return jnp.matmul(_t(linv, True), linv)


@register("_linalg_trmm", aliases=("linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    a = _t(A, transpose)
    return alpha * (jnp.matmul(B, a) if rightside else jnp.matmul(a, B))


@register("_linalg_trsm", aliases=("linalg_trsm",))
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    if rightside:
        # X A = alpha B  ->  A^T X^T = alpha B^T
        x = jsl.solve_triangular(_t(A, not transpose), _t(B, True),
                                 lower=(lower != transpose))
        return alpha * _t(x, True)
    return alpha * jsl.solve_triangular(_t(A, transpose), B,
                                        lower=(lower != transpose))


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("_linalg_syrk", aliases=("linalg_syrk",))
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = _t(A, transpose)
    return alpha * jnp.matmul(a, _t(a, True))


@register("_linalg_extractdiag", aliases=("linalg_extractdiag",))
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", aliases=("linalg_makediag",))
def linalg_makediag(A, offset=0):
    n = A.shape[-1] + abs(offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    idx = jnp.arange(A.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    return out.at[..., r, c].set(A)


@register("_linalg_extracttrian", aliases=("linalg_extracttrian",))
def linalg_extracttrian(A, offset=0, lower=True):
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower else jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register("_linalg_inverse", aliases=("linalg_inverse",))
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("_linalg_det", aliases=("linalg_det",))
def linalg_det(A):
    return jnp.linalg.det(A)


@register("_linalg_slogdet", aliases=("linalg_slogdet",))
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


@register("_linalg_gelqf", aliases=("linalg_gelqf",), num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization A = L·Q with Q's rows orthonormal (ref:
    tensor/la_op.cc _linalg_gelqf) — computed as QR of Aᵀ: Aᵀ = Q̃R̃
    gives L = R̃ᵀ, Q = Q̃ᵀ."""
    qt, rt = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(rt, -1, -2), jnp.swapaxes(qt, -1, -2)


@register("_linalg_syevd", aliases=("linalg_syevd",), num_outputs=2)
def linalg_syevd(A):
    """Symmetric eigendecomposition A = Uᵀ·diag(L)·U (ref:
    tensor/la_op.cc _linalg_syevd — rows of U are the eigenvectors,
    eigenvalues ascending)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("khatri_rao", num_inputs=None)
def khatri_rao(*args):
    # column-wise Kronecker product: (n, k) x (m, k) -> (n*m, k)
    out = args[0]
    for b in args[1:]:
        out = (out[:, None, :] * b[None, :, :]).reshape(-1, out.shape[-1])
    return out
