"""Execution-engine facade.

The reference's dependency engine (src/engine/threaded_engine_perdevice.cc)
exists to overlap op execution with the Python thread and serialize writers
per variable. On TPU the PJRT runtime already *is* that engine: op dispatch is
async (returns futures immediately), per-device execution is stream-ordered,
and data dependencies are tracked by buffer. This module keeps the reference's
user-facing control points:

  - MXNET_ENGINE_TYPE=NaiveEngine  -> synchronous execution after every op
    (the determinism/debug switch, ref: src/engine/engine.cc:32-48)
  - waitall()/wait_for_var         -> barriers on the async stream
  - exception propagation          -> jax raises deferred errors at
    block_until_ready, matching the reference's rethrow-at-WaitForVar
    contract (ref: src/engine/threaded_engine.cc:472-479)
"""
from __future__ import annotations

import jax

from .base import get_env

_naive = get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice") == "NaiveEngine"
_pending = []
_PENDING_MAX = 64


def is_naive():
    return _naive


def set_engine_type(name):
    global _naive
    _naive = name == "NaiveEngine"


def on_op_executed(outputs):
    """Called by the nd dispatch layer after each eager op."""
    import jax.core
    if any(isinstance(o, jax.core.Tracer) for o in outputs):
        return  # inside a jit trace: the compiled step is the engine op
    if _naive:
        for o in outputs:
            jax.block_until_ready(o)
        return
    # keep a small window of in-flight results so waitall() has handles to
    # block on without retaining everything (stream ordering does the rest)
    _pending.extend(outputs)
    if len(_pending) > _PENDING_MAX:
        del _pending[: len(_pending) - _PENDING_MAX]


def waitall():
    """Block until all pushed work completes (MXNDArrayWaitAll analogue).

    Device streams execute in order, so blocking on the most recently
    dispatched arrays implies completion of everything before them.
    """
    for o in _pending:
        try:
            jax.block_until_ready(o)
        except Exception:
            # waitall surfaces the first pending error, like WaitForAll
            _pending.clear()
            raise
    _pending.clear()


def wait_for_var(arr):
    jax.block_until_ready(arr)
