"""Execution-engine facade.

The reference's dependency engine (src/engine/threaded_engine_perdevice.cc)
exists to overlap op execution with the Python thread and serialize writers
per variable. On TPU the PJRT runtime already *is* that engine: op dispatch is
async (returns futures immediately), per-device execution is stream-ordered,
and data dependencies are tracked by buffer. This module keeps the reference's
user-facing control points:

  - MXNET_ENGINE_TYPE=NaiveEngine  -> synchronous execution after every op
    (the determinism/debug switch, ref: src/engine/engine.cc:32-48)
  - waitall()/wait_for_var         -> barriers on the async stream
  - exception propagation          -> jax raises deferred errors at
    block_until_ready, matching the reference's rethrow-at-WaitForVar
    contract (ref: src/engine/threaded_engine.cc:472-479)
"""
from __future__ import annotations

import jax

from .base import get_env
from .telemetry import metrics as _tm
from . import tracing as _tracing

# cached SERIES (reset-safe) — per-op cost is one lock+add
_met = _tm.lazy_metrics(lambda reg: {
    "eager": reg.counter(
        "mx_engine_eager_ops_total",
        "eager ops observed by the dispatch layer").labels(),
    "host_ops": reg.counter(
        "mx_host_engine_ops_total",
        "host tasks pushed to the native dependency engine").labels(),
    "inflight": reg.gauge(
        "mx_host_engine_inflight",
        "host-engine tasks submitted and not yet dispatched").labels(),
})

_naive = get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice") == "NaiveEngine"
# newest in-flight result PER DEVICE: device streams execute in order, so
# blocking on the most recent array touching each device fences everything
# dispatched before it on that device. A bounded global window (the old
# scheme) could drop the only handle living on some device of a sharded
# output, leaving waitall() blind to that device's stream.
_newest_by_device = {}


def is_naive():
    return _naive


def set_engine_type(name):
    global _naive
    _naive = name == "NaiveEngine"


def on_op_executed(outputs):
    """Called by the nd dispatch layer after each eager op."""
    import jax.core
    if any(isinstance(o, jax.core.Tracer) for o in outputs):
        return  # inside a jit trace: the compiled step is the engine op
    if _tm.enabled():
        _met()["eager"].inc()
    if _naive:
        for o in outputs:
            jax.block_until_ready(o)
        return
    for o in outputs:
        try:
            devs = o.devices()
        except Exception:  # noqa: BLE001 — committed scalars etc.
            devs = ()
        for d in devs:
            _newest_by_device[d] = o


def waitall():
    """Block until all pushed work completes (MXNDArrayWaitAll analogue).

    Device streams execute in order, so blocking on the newest array on
    each device implies completion of everything before it there.
    """
    try:
        # snapshot first: loader/prefetch threads may insert new device
        # keys mid-iteration; dedupe because one sharded array may be
        # the newest entry on many devices
        snapshot = list(_newest_by_device.values())
        for o in {id(v): v for v in snapshot}.values():
            jax.block_until_ready(o)
    except Exception:
        # waitall surfaces the first pending error, like WaitForAll
        _newest_by_device.clear()
        raise
    _newest_by_device.clear()
    if _host is not None:
        _host.wait_all()


def wait_for_var(arr):
    jax.block_until_ready(arr)


# ---------------------------------------------------------------------------
# native host engine — the C++ dependency scheduler (_native/core.cc).
# XLA/PJRT is the engine for DEVICE work; this one carries the reference's
# ThreadedEngine semantics (serialize writers per var, parallel readers,
# poison-on-failure) for HOST-side framework work: decode, prefetch, IO
# (ref: include/mxnet/engine.h:115, src/engine/threaded_engine.h:66).
# ---------------------------------------------------------------------------

_host = None


class _HostEngine:
    def __init__(self):
        import atexit
        import ctypes
        import itertools

        from ._native import ENGINE_OP_CFUNC, load_core
        self._lib = load_core()
        self._ctypes = ctypes
        self._inflight = {}  # tag -> python fn
        self._tags = itertools.count(1)  # atomic under the GIL
        # ONE persistent libffi trampoline for every op: the C side only
        # ever calls this thunk (which lives for the engine's lifetime),
        # and the per-op Python closure is looked up by the tag passed as
        # the op's void* arg — no thunk is ever freed while C might be
        # executing it
        self._cb = ENGINE_OP_CFUNC(self._dispatch)
        self._lib.mxtpu_engine_start(0)  # MXNET_CPU_WORKER_NTHREADS
        # drain + stop while the interpreter is still alive: the C++
        # static destructor runs after Py_Finalize, when invoking a
        # pending Python callback would abort the process
        atexit.register(self._shutdown)

    def _shutdown(self):
        try:
            self._lib.mxtpu_engine_wait_all()
        finally:
            self._lib.mxtpu_engine_stop()

    def _dispatch(self, argp):
        fn = self._inflight.pop(int(argp or 0), None)
        if _tm.enabled():
            _met()["inflight"].set(len(self._inflight))
        if fn is None:
            return 2
        try:
            fn()
            return 0
        except Exception:  # noqa: BLE001 — crosses the C boundary
            import traceback
            traceback.print_exc()
            return 1

    def new_var(self):
        return int(self._lib.mxtpu_engine_new_var())

    def delete_var(self, var):
        self._lib.mxtpu_engine_delete_var(var)

    def push(self, fn, read_vars=(), write_vars=()):
        """Run `fn()` on a worker thread once its var deps are satisfied.
        A raised exception poisons the write vars (rethrown at wait)."""
        if _tm.enabled():
            _met()["host_ops"].inc()
        if _tracing.enabled():
            # the async push→exec edge: capture the pusher's context
            # here, reopen it as the exec span's parent on whichever
            # engine worker thread runs the task
            ctx = _tracing.context()
            if ctx[0]:
                task, label = fn, getattr(fn, "__qualname__", "task")

                def fn(_task=task, _ctx=ctx, _label=label):
                    with _tracing.span_at(_ctx, "host_engine_exec",
                                          cat="engine", task=_label):
                        _task()
        if _naive:
            # determinism switch serializes host tasks too
            # (ref: src/engine/naive_engine.cc:50 executes on push)
            fn()
            return
        ct = self._ctypes
        tag = next(self._tags)
        self._inflight[tag] = fn
        if _tm.enabled():
            _met()["inflight"].set(len(self._inflight))
        nr, nw = len(read_vars), len(write_vars)
        r = (ct.c_int64 * nr)(*read_vars) if nr else None
        w = (ct.c_int64 * nw)(*write_vars) if nw else None
        if self._lib.mxtpu_engine_push(self._cb, ct.c_void_p(tag),
                                       r, nr, w, nw) != 0:
            self._inflight.pop(tag, None)
            raise RuntimeError(self._lib.mxtpu_get_last_error().decode())

    def wait_for_var(self, var):
        if self._lib.mxtpu_engine_wait_for_var(var) != 0:
            raise RuntimeError(self._lib.mxtpu_get_last_error().decode())

    def wait_all(self):
        if self._lib.mxtpu_engine_wait_all() != 0:
            raise RuntimeError(self._lib.mxtpu_get_last_error().decode())


def host_engine():
    """The process-wide native host engine (built on first use)."""
    global _host
    if _host is None:
        _host = _HostEngine()
    return _host
