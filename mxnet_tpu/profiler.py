"""Profiler with chrome://tracing output (ref: src/profiler/profiler.h:
87,256,304 Profiler/ProfileStat, python/mxnet/profiler.py).

The reference's engine stamps every pushed operator with start/stop
times and dumps a chrome-trace JSON plus an aggregate table
(src/profiler/aggregate_stats.cc). Here the instrumented seams are the
eager dispatch layer (``ndarray.invoke``), the graph executor
(forward/backward), and any user code via the ProfileTask/Event/
Counter/Frame objects — written into one ``traceEvents`` JSON that
chrome://tracing and Perfetto load directly. XLA-internal per-kernel
timing lives behind ``jax.profiler`` (TensorBoard format) and can be
captured alongside via ``set_config(xla_trace_dir=...)``.

Env: ``MXNET_PROFILER_AUTOSTART=1`` starts profiling at import
(ref: docs/faq/env_var.md).
"""
from __future__ import annotations

import json
import os
import threading

from .base import MXNetError
from .telemetry import metrics as _tm
from .tracing import clock as _clock

_lock = threading.Lock()
_events = []          # chrome trace event dicts
_counters = {}
_state = "stop"
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "xla_trace_dir": None,
}
_xla_session = None


def _now_us():
    # ONE clock source for every timeline: tracing spans and these
    # chrome-trace events share tracing.clock's process epoch, so a
    # merged Perfetto artifact never interleaves two time axes
    # (a private perf_counter offset here did exactly that pre-PR 5)
    return _clock.rel_us(_clock.now_ns())


# dist kvstore whose servers remote profiler commands reach; installed
# automatically when a dist KVStore connects (ref: profiler.py
# set_kvstore_handle + kvstore.h:380 SendCommandToServers)
_kv_conn = None


def set_kvstore_handle(kv):
    """Register the dist kvstore that profile_process='server' calls
    route through (ref: python/mxnet/profiler.py set_kvstore_handle)."""
    global _kv_conn
    _kv_conn = getattr(kv, "_conn", kv)


def _send_server(directive):
    if _kv_conn is None:
        raise MXNetError(
            "profile_process='server' needs a connected dist kvstore "
            "(create mx.kv.create('dist_sync') first, ref: "
            "kvstore.h:387's warning for the same misuse)")
    _kv_conn.send_profiler_command(directive)


def set_config(profile_process="worker", **kwargs):
    if profile_process == "server":
        _send_server({"cmd": "set_config", "kwargs": kwargs})
        return
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError(f"unknown profiler config keys {sorted(unknown)}")
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """'run' starts collection, 'stop' ends it (ref: profiler.py
    set_state; MXSetProcessProfilerState)."""
    global _state, _xla_session
    if profile_process == "server":
        _send_server({"cmd": "set_state", "state": state})
        return
    if state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    if state == "run" and _state != "run":
        if _config["xla_trace_dir"]:
            import jax
            jax.profiler.start_trace(_config["xla_trace_dir"])
            _xla_session = True
    if state == "stop" and _state == "run" and _xla_session:
        import jax
        jax.profiler.stop_trace()
        _xla_session = None
        # the capture is now on disk: remember where, so
        # last_xplane_dir()/op_attribution() can analyze it without
        # the caller re-plumbing the directory
        _last_xplane_dir[0] = _config["xla_trace_dir"]
    _state = state


_last_xplane_dir = [None]


def last_xplane_dir():
    """The most recent completed ``xla_trace_dir`` capture (set_config
    + set_state('run'→'stop')), or None."""
    return _last_xplane_dir[0]


def op_attribution(compiled=None, hlo_text=None, profile_dir=None,
                   **kwargs):
    """Measured per-op attribution for the last (or given) xplane
    capture, joined against a cost ledger built from ``compiled`` /
    ``hlo_text`` — the profiler-side door into
    ``mxnet_tpu.profiling`` (docs/observability.md "MFU accounting &
    roofline")."""
    from . import profiling
    profile_dir = profile_dir or last_xplane_dir()
    if profile_dir is None:
        raise MXNetError(
            "no xplane capture recorded: run with "
            "set_config(xla_trace_dir=...) + set_state('run'/'stop'), "
            "or pass profile_dir=")
    return profiling.analyze_dir(profile_dir, compiled=compiled,
                                 hlo_text=hlo_text, **kwargs)


def state():
    return _state


def is_running():
    return _state == "run"


def pause(profile_process="worker"):
    if profile_process == "server":
        _send_server({"cmd": "pause"})
        return
    set_state("stop")


def resume(profile_process="worker"):
    if profile_process == "server":
        _send_server({"cmd": "resume"})
        return
    set_state("run")


def record_event(name, cat, start_us, dur_us, args=None, tid=None):
    """Append one complete ('X') chrome trace event."""
    if _state != "run":
        return
    ev = {"name": name, "cat": cat, "ph": "X",
          "ts": start_us, "dur": dur_us, "pid": 0,
          "tid": tid if tid is not None else threading.get_ident() % 1000}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)


class _timed:
    """Context manager timing a region into the trace."""

    def __init__(self, name, cat):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self.start = _now_us()
        return self

    def __exit__(self, *exc):
        record_event(self.name, self.cat, self.start,
                     _now_us() - self.start)
        return False


def timed_operator(name):
    return _timed(name, "operator")


def timed_region(name, cat="region"):
    return _timed(name, cat)


def dump(finished=True, profile_process="worker"):
    """Write the chrome-trace JSON to the configured filename."""
    if profile_process == "server":
        _send_server({"cmd": "dump"})
        return
    with _lock:
        events = list(_events)
        if finished:
            _events.clear()
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


def dumps(reset=False, format="table"):
    """Aggregate stats string (ref: MXAggregateProfileStatsPrint)."""
    with _lock:
        events = list(_events)
        if reset:
            _events.clear()
    agg = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue  # counters/markers carry no duration
        name = ev["name"]
        st = agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        st[0] += 1
        st[1] += ev["dur"]
        st[2] = min(st[2], ev["dur"])
        st[3] = max(st[3], ev["dur"])
    lines = [f"{'Name':<40}{'Count':>8}{'Total(us)':>14}"
             f"{'Min(us)':>12}{'Max(us)':>12}{'Avg(us)':>12}"]
    for name, (cnt, tot, mn, mx) in sorted(
            agg.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40}{cnt:>8}{tot:>14.1f}{mn:>12.1f}"
                     f"{mx:>12.1f}{tot / cnt:>12.1f}")
    return "\n".join(lines)


# -- kvstore recovery telemetry -------------------------------------------
# The dist transport reports every recovery incident (reconnect storms,
# budget exhaustions) here, independent of the run/stop profiling state —
# the bench supervisor needs to answer "WHY did this distributed run
# degrade" even when nobody armed the profiler. When the profiler IS
# running, each incident also lands in the chrome trace (category
# "kvstore_recovery") so waits line up against the op timeline.
#
# Since PR 4 the COUNTERS live on the telemetry metrics registry
# (mx_recovery_* families, so they ride every snapshot/Prometheus
# export); this module keeps the bounded incident list for
# recovery_incidents()/"last" and recovery_summary() as a compatibility
# shim over the registry.
_recovery_incidents = []
_RECOVERY_KEEP = 256

_recovery_met = _tm.lazy_metrics(lambda reg: {
    "incidents": reg.counter(
        "mx_recovery_incidents_total",
        "kvstore/checkpoint recovery incidents by outcome "
        "(recovered/exhausted/worker_resume/checkpoint_rejected)",
        labelnames=("outcome",)),
    "attempts": reg.counter(
        "mx_recovery_attempts_total",
        "resend attempts across all recovery incidents").labels(),
    "reconnects": reg.counter(
        "mx_recovery_reconnects_total",
        "successful transport reconnects during recovery").labels(),
    "backoff_ms": reg.counter(
        "mx_recovery_backoff_wait_ms_total",
        "milliseconds slept in recovery backoff").labels(),
})


def note_recovery(args):
    """Record one recovery incident dict (op, req_id, outcome,
    attempts, backoff_wait_ms, ...) from the kvstore transport.
    Unconditional (not gated on MXTPU_TELEMETRY): recovery telemetry is
    the 'why did this run degrade' record and must survive a disabled
    hot-path collection."""
    with _lock:
        _recovery_incidents.append(dict(args))
        del _recovery_incidents[:-_RECOVERY_KEEP]
    m = _recovery_met()
    m["incidents"].labels(outcome=str(args.get("outcome", "?"))).inc()
    m["attempts"].inc(int(args.get("attempts", 0)))
    m["reconnects"].inc(int(args.get("reconnects", 0)))
    m["backoff_ms"].inc(float(args.get("backoff_wait_ms", 0.0)))
    record_event("kvstore_recovery:%s" % args.get("outcome", "?"),
                 "kvstore_recovery", _now_us(), 0, args=dict(args))


def note_worker_resume(args):
    """Record one worker auto-resume (checkpoint.py
    CheckpointManager.resume_latest): step, checkpoint path, restart
    count — the whole-job-survivability half of recovery telemetry."""
    note_recovery(dict(args, outcome="worker_resume"))


def note_checkpoint_rejected(args):
    """Record one torn/corrupt checkpoint skipped at resume time
    (CRC/manifest validation failed)."""
    note_recovery(dict(args, outcome="checkpoint_rejected"))


def recovery_incidents():
    with _lock:
        return [dict(a) for a in _recovery_incidents]


def recovery_summary():
    """Aggregate recovery telemetry: the structured 'why it degraded'
    record the bench supervisor folds into its JSON artifact.

    Compatibility shim since PR 4: the counts come from the telemetry
    registry's mx_recovery_* families (unbounded, exported everywhere),
    not from re-summing the bounded incident list — only "last" still
    reads the retained incidents."""
    with _lock:
        last = dict(_recovery_incidents[-1]) if _recovery_incidents \
            else None
    m = _recovery_met()
    by_outcome = {s.labels["outcome"]: s.value
                  for s in m["incidents"].series()}
    if not any(by_outcome.values()):
        # counters zeroed (registry().reset(), e.g. the before/after
        # perf-diff workflow) while the bounded incident list survives:
        # report a consistent all-zero summary; raw history stays
        # available via recovery_incidents()
        last = None
    return {
        "incidents": int(round(sum(by_outcome.values()))),
        "recovered": int(round(by_outcome.get("recovered", 0))),
        "exhausted": int(round(by_outcome.get("exhausted", 0))),
        "attempts": int(round(m["attempts"].value)),
        "reconnects": int(round(m["reconnects"].value)),
        "backoff_wait_ms": round(m["backoff_ms"].value, 3),
        "worker_resumes": int(round(by_outcome.get("worker_resume", 0))),
        "checkpoints_rejected": int(round(
            by_outcome.get("checkpoint_rejected", 0))),
        "last": last,
    }


# -- user-defined instrumentation objects (ref: profiler.h:556-837) -------
class Domain:
    def __init__(self, name):
        self.name = name


class Task:
    def __init__(self, domain, name):
        self.name = name
        self.domain = domain
        self._start = None

    def start(self):
        self._start = _now_us()

    def stop(self):
        if self._start is not None:
            record_event(self.name, f"task:{self.domain.name}",
                         self._start, _now_us() - self._start)
            self._start = None


class Event(Task):
    pass


class Frame(Task):
    pass


class Counter:
    """User-visible profiler counter (ref: profiler.h:752 Counter).

    Thread-safe: increment/decrement are read-modify-writes, and the
    host engine's worker threads (engine.py _HostEngine) legitimately
    bump one counter concurrently — unlocked ``self._value += delta``
    loses updates under that interleaving (PR 4 audit). The per-counter
    lock is taken BEFORE the module ``_lock`` in ``set_value``; nothing
    acquires them in the reverse order."""

    def __init__(self, domain, name, value=0):
        self.name = name
        self.domain = domain
        self._value = value
        self._vlock = threading.Lock()

    def set_value(self, value):
        with self._vlock:
            self._value = value
        if _state == "run":
            with _lock:
                _events.append({"name": self.name, "ph": "C",
                                "ts": _now_us(), "pid": 0,
                                "args": {self.name: value}})

    def increment(self, delta=1):
        with self._vlock:
            self._value += delta
            value = self._value
        if _state == "run":
            with _lock:
                _events.append({"name": self.name, "ph": "C",
                                "ts": _now_us(), "pid": 0,
                                "args": {self.name: value}})

    def decrement(self, delta=1):
        self.increment(-delta)

    @property
    def value(self):
        with self._vlock:
            return self._value


def marker(name, scope="process"):
    if _state == "run":
        with _lock:
            _events.append({"name": name, "ph": "i", "ts": _now_us(),
                            "pid": 0, "s": scope[0]})


# instant-marker alias used by the reference API
mark = marker

if os.environ.get("MXNET_PROFILER_AUTOSTART") == "1":
    set_state("run")
