"""RecordIO file format (ref: python/mxnet/recordio.py + dmlc-core recordio +
src/io/image_recordio.h).

Byte-compatible with the reference's format: records framed by kMagic
(0xced7230a) + length word (upper 3 bits = continuation flag), payloads padded
to 4 bytes; IRHeader packs (flag, label, id, id2) ahead of image payloads;
.idx files map integer keys to byte offsets for random access.
"""
from __future__ import annotations

import ctypes
import io
import os
import queue
import struct
import threading
from collections import namedtuple

import numpy as np

from .base import MXNetError

_MAGIC = 0xCED7230A
_LFLAG_BITS = 29
_LFLAG_MASK = (1 << _LFLAG_BITS) - 1

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential reader/writer (ref: recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.fid = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.fid = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fid = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag!r}")
        self.pid = os.getpid()

    def close(self):
        if self.fid is not None and not self.fid.closed:
            self.fid.close()

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["fid"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.fid.tell()

    def write(self, buf):
        assert self.writable
        length = len(buf)
        upper = 0  # single-record framing (no continuation chunks needed)
        self.fid.write(struct.pack("<II", _MAGIC,
                                   (upper << _LFLAG_BITS) | length))
        self.fid.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.fid.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        hdr = self.fid.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _MAGIC:
            raise MXNetError("invalid RecordIO magic")
        length = lrec & _LFLAG_MASK
        buf = self.fid.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fid.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with .idx sidecar
    (ref: recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)

    def close(self):
        if self.writable and self.fid is not None and not self.fid.closed:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.fid.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


def pack(header, s):
    """Pack IRHeader + payload (ref: recordio.py pack). Scalar labels go in
    the header (flag=0); vector labels set flag=len and follow the header."""
    header = IRHeader(*header)
    label = header.label
    if np.isscalar(label):
        return struct.pack(_IR_FORMAT, int(header.flag), float(label),
                           header.id, header.id2) + s
    label = np.asarray(label, np.float32).ravel()
    hdr = struct.pack(_IR_FORMAT, len(label), 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Unpack a record into (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode image + pack (cv2, then PIL, then raw npy; decoded arrays
    are RGB HWC in the PIL path)."""
    try:
        import cv2
        ret, buf = cv2.imencode(img_fmt, img,
                                [cv2.IMWRITE_JPEG_QUALITY, quality])
        assert ret
        return pack(header, buf.tobytes())
    except ImportError:
        pass
    try:
        from PIL import Image
        bio = io.BytesIO()
        fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG"}.get(
            img_fmt.lstrip("."), "JPEG")
        arr = np.asarray(img)
        if arr.dtype != np.uint8:
            arr = np.clip(arr, 0, 255).astype(np.uint8)
        Image.fromarray(arr).save(bio, fmt, quality=quality)
        return pack(header, bio.getvalue())
    except ImportError:
        bio = io.BytesIO()
        np.save(bio, np.asarray(img))
        return pack(header, bio.getvalue())


def cv2_present():
    """Whether unpack_img/decode_payload would decode through cv2
    (which yields BGR) — the one place callers consult to decide
    channel normalization."""
    import importlib.util
    return importlib.util.find_spec("cv2") is not None


def decode_payload(payload, iscolor=-1):
    """Decode one record payload to an array: raw .npy passthrough,
    else cv2 (BGR, the reference's convention) or PIL (RGB)."""
    if payload[:6] == b"\x93NUMPY":
        return np.load(io.BytesIO(payload))
    try:
        import cv2
        return cv2.imdecode(np.frombuffer(payload, np.uint8), iscolor)
    except ImportError:
        try:
            from PIL import Image
            return np.asarray(Image.open(io.BytesIO(payload))
                              .convert("RGB"))
        except ImportError:
            raise MXNetError(
                "cannot decode image without cv2 or PIL; pack with "
                "raw npy payloads in this environment")


def unpack_img(s, iscolor=-1):
    header, payload = unpack(s)
    return header, decode_payload(payload, iscolor)


def load_record_offsets(path):
    """Byte offsets of every record in a .rec file: from the ``.idx``
    sidecar when present, else one framing scan (seeks only — payloads
    are never retained). The shared index the random-access iterators
    and the sharded pipeline both build on."""
    idx_path = os.path.splitext(path)[0] + ".idx"
    if os.path.isfile(idx_path):
        offs = []
        with open(idx_path) as f:
            for line in f:
                parts = line.strip().split("\t")
                if len(parts) >= 2:
                    offs.append(int(parts[1]))
        if offs:
            return offs
    offs = []
    with open(path, "rb") as f:
        f.seek(0, 2)
        end = f.tell()
        pos = 0
        while pos + 8 <= end:
            f.seek(pos)
            magic, lrec = struct.unpack("<II", f.read(8))
            if magic != _MAGIC:
                raise MXNetError(f"invalid RecordIO magic at {pos}")
            offs.append(pos)
            length = lrec & _LFLAG_MASK
            pos += 8 + length + (4 - length % 4) % 4
    return offs


class RecordIOStreamReader:
    """Streaming shard reader: a background thread reads ahead
    ``readahead_mb`` of raw bytes in large chunks while the caller
    consumes parsed records — so epoch-scale datasets stream from
    disk/remote mounts without local materialization, and read I/O
    overlaps decode (the io pipeline's decode workers sit downstream).

    Records are framed on the wire (kMagic + length word), so a record
    may straddle a chunk boundary; the parser carries the partial tail
    into the next chunk. Iterating yields ``(byte_offset, record)``
    pairs for the byte range ``[start, stop)`` of ``uri`` (``stop=None``
    = end of file). ``start`` must sit on a record boundary.
    """

    #: one read() granularity; readahead_mb bounds how many of these
    #: may sit parsed-ahead in the queue
    CHUNK_BYTES = 4 << 20

    def __init__(self, uri, start=0, stop=None, readahead_mb=None,
                 chunk_bytes=None):
        from .base import get_env
        if readahead_mb is None:
            readahead_mb = get_env("MXTPU_IO_READAHEAD_MB", 64, int)
        self._chunk = int(chunk_bytes or self.CHUNK_BYTES)
        depth = max(1, (int(readahead_mb) << 20) // self._chunk)
        self._uri = uri
        self._start = int(start)
        self._stop = stop
        self._queue = queue.Queue(maxsize=depth)
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._read_loop,
                                        daemon=True)
        self._thread.start()

    def _read_loop(self):
        try:
            with open(self._uri, "rb") as f:
                if self._stop is None:
                    f.seek(0, 2)
                    stop = f.tell()
                else:
                    stop = int(self._stop)
                f.seek(self._start)
                pos = self._start
                while pos < stop and not self._stop_evt.is_set():
                    chunk = f.read(min(self._chunk, stop - pos))
                    if not chunk:
                        break
                    pos += len(chunk)
                    self._put(chunk)
        except Exception as e:  # noqa: BLE001 — surface at the consumer
            self._put(e)
            return
        self._put(None)

    def _put(self, item):
        while not self._stop_evt.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        buf = b""
        pos = self._start
        while True:
            item = self._queue.get()
            if item is None:
                break
            if isinstance(item, Exception):
                raise item
            buf = buf + item if buf else item
            # drain complete frames by cursor (no per-record buffer
            # reslicing); a partial frame tail carries into the next
            # chunk (chunk-boundary records)
            off = 0
            while len(buf) - off >= 8:
                magic, lrec = struct.unpack_from("<II", buf, off)
                if magic != _MAGIC:
                    raise MXNetError(
                        f"invalid RecordIO magic at {pos}")
                length = lrec & _LFLAG_MASK
                framed = 8 + length + (4 - length % 4) % 4
                if len(buf) - off < framed:
                    break
                yield pos, buf[off + 8:off + 8 + length]
                off += framed
                pos += framed
            buf = buf[off:]
        if buf:
            raise MXNetError(
                f"truncated record at byte {pos} (stream ended inside "
                "a frame)")

    def close(self):
        self._stop_evt.set()
        # unblock a producer stuck on put()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass
