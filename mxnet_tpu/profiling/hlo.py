"""Optimized-HLO text parser + per-instruction analytic cost model.

XLA's compiled-executable ``cost_analysis()`` reports one aggregate
FLOP/byte total for the whole module — useless for answering *where*
the chip time goes. This module parses the post-optimization HLO text
(``lowered.compile().as_text()``, identical format on CPU and TPU, so
every ledger test runs chip-free) into instructions with shapes,
opcodes, called computations and jax ``op_name`` metadata, and prices
each instruction analytically:

- ``dot``: 2 * out_elems * K (K = product of lhs contracting dims),
- ``convolution``: 2 * out_elems * kernel_spatial * rhs_input_features
  (the rhs 'i' dim is already per-group, so grouped/depthwise convs
  price correctly),
- ``fusion`` / ``call`` / ``while`` / ``conditional``: the called
  computation's instructions summed (a while body is priced for ONE
  trip — static text has no trip count; the xplane join supplies the
  measured truth),
- elementwise / reduce / rng: one flop per element touched,
- everything else: 0 flops (pure data movement).

Bytes are the instruction's own operand + output footprints — for a
fusion that is exactly the memory-traffic win the fusion bought, since
internal producer/consumer pairs never touch HBM.

Stdlib only: no jax import, so ``tools/mfu_report.py`` can price a
committed ``.hlo.txt`` artifact anywhere the repo is checked out.
"""
from __future__ import annotations

import math
import re

# dtype -> bytes per element (HLO spellings)
_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "f8e3m4": 1, "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "tf32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "token": 0, "opaque": 0,
}

# one entry per *array* component: "f32[2,3]{1,0}" or "(f32[2], s32[])"
_ARRAY_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")

_COMMS_OPCODES = {
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast", "all-reduce-start",
    "all-gather-start", "collective-permute-start", "send", "recv",
    "send-done", "recv-done", "partition-id", "replica-id",
}

# opcodes priced at ~1 flop per output element (elementwise + cheap
# transcendentals — the roofline bound for these is bytes anyway)
_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "power", "remainder", "and", "or", "xor", "not", "negate", "abs",
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "sqrt", "rsqrt", "cbrt", "sign", "cosine", "sine", "tan", "tanh",
    "atan2", "erf", "logistic", "floor", "ceil", "round-nearest-afz",
    "round-nearest-even", "compare", "select", "clamp", "convert",
    "is-finite", "shift-left", "shift-right-arithmetic",
    "shift-right-logical", "popcnt", "clz", "stochastic-convert",
}

# free / pure-movement opcodes: never worth a ledger row of their own
TRIVIAL_OPCODES = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "after-all", "opt-barrier", "partition-id",
    "replica-id", "iota",
}


def _shape_components(shape_text):
    """[(dtype, elems)] for every array component of a shape string
    (tuples flatten; layout annotations ignored)."""
    out = []
    for dtype, dims in _ARRAY_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue  # e.g. a stray identifier that looked shape-like
        elems = 1
        if dims:
            elems = math.prod(int(d) for d in dims.split(","))
        out.append((dtype, elems))
    return out


def shape_elems(shape_text):
    return sum(e for _, e in _shape_components(shape_text))


def shape_bytes(shape_text):
    return sum(e * _DTYPE_BYTES[d] for d, e in
               _shape_components(shape_text))


class Instr:
    """One parsed HLO instruction."""

    __slots__ = ("name", "opcode", "shape", "operand_shapes", "operands",
                 "attrs", "op_name", "calls", "is_root")

    def __init__(self, name, opcode, shape, operand_shapes, operands,
                 attrs, op_name, calls, is_root):
        self.name = name
        self.opcode = opcode
        self.shape = shape
        self.operand_shapes = operand_shapes
        self.operands = operands          # operand instruction names
        self.attrs = attrs                # raw trailing attr text
        self.op_name = op_name            # jax metadata op_name path
        self.calls = calls                # called computation names
        self.is_root = is_root

    def __repr__(self):
        return "<Instr %s = %s %s>" % (self.name, self.shape, self.opcode)


_COMP_HEAD = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^)]*\))?\s*"
                        r"(?:->\s*.+?)?\s*{\s*$")
_INSTR_HEAD = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.\-]+)\s+=\s+"
    r"(\(.*?\)|[a-z0-9]+\[[\d,]*\](?:{[^}]*})?)\s+"
    r"([\w\-]+)\(")


def _split_args(line, open_idx):
    """(args, tail) splitting at the paren that matches ``open_idx``.
    Operand lists may contain nested parens (tuple-typed operands) and
    the trailing metadata contains parens inside quoted strings, so a
    regex can't do this — a depth scan can."""
    depth = 0
    in_str = False
    i = open_idx
    n = len(line)
    while i < n:
        c = line[i]
        if in_str:
            if c == '"' and line[i - 1] != "\\":
                in_str = False
        elif c == '"':
            in_str = True
        elif c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return line[open_idx + 1:i], line[i + 1:]
        i += 1
    return line[open_idx + 1:], ""
_OPERAND_RE = re.compile(
    r"([a-z0-9]+\[[\d,]*\])(?:{[^}]*})?\s+%?([\w.\-]+)")
_METADATA_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"(?:calls|to_apply|body|condition|branch_"
                       r"computations)=\{?%?([\w.\-, %]+)\}?")


class Module:
    """Parsed HLO module: {computation name: [Instr]} + entry name."""

    def __init__(self, name, computations, entry):
        self.name = name
        self.computations = computations
        self.entry = entry

    @property
    def entry_instructions(self):
        return self.computations.get(self.entry, [])

    def all_instruction_names(self):
        names = set()
        for instrs in self.computations.values():
            names.update(i.name for i in instrs)
        return names


def parse_module(text):
    """Parse optimized HLO text into a :class:`Module`."""
    mod_name = "hlo"
    m = re.search(r"^HloModule\s+([\w.\-]+)", text, re.M)
    if m:
        mod_name = m.group(1)
    computations = {}
    entry = None
    cur = None
    for line in text.splitlines():
        stripped = line.strip()
        if cur is None:
            if not stripped or stripped.startswith(("HloModule", "//")):
                continue
            head = _COMP_HEAD.match(stripped)
            if head and stripped.endswith("{"):
                cur = head.group(2)
                computations[cur] = []
                if head.group(1):
                    entry = cur
            continue
        if stripped == "}":
            cur = None
            continue
        im = _INSTR_HEAD.match(line)
        if im is None:
            continue
        is_root, name, shape, opcode = im.groups()
        args, tail = _split_args(line, im.end() - 1)
        operand_shapes = []
        operands = []
        for oshape, oname in _OPERAND_RE.findall(args):
            operand_shapes.append(oshape)
            operands.append(oname)
        md = _METADATA_RE.search(tail)
        calls = []
        for cm in _CALLS_RE.finditer(tail):
            calls.extend(c.strip().lstrip("%") for c in
                         cm.group(1).split(",") if c.strip())
        computations[cur].append(Instr(
            name=name, opcode=opcode, shape=shape,
            operand_shapes=operand_shapes, operands=operands,
            attrs=tail, op_name=md.group(1) if md else None,
            calls=calls, is_root=bool(is_root)))
    if entry is None and computations:
        # fall back to the lexically last computation (XLA prints the
        # entry last when the ENTRY marker is absent)
        entry = list(computations)[-1]
    return Module(mod_name, computations, entry)


def _dot_flops(instr):
    out = shape_elems(instr.shape)
    k = 1
    m = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", instr.attrs)
    if m and instr.operand_shapes:
        lhs = _ARRAY_RE.search(instr.operand_shapes[0])
        if lhs:
            dims = [int(d) for d in lhs.group(2).split(",") if d]
            for i in (int(x) for x in m.group(1).split(",") if x):
                if i < len(dims):
                    k *= dims[i]
    return 2 * out * k


def _conv_flops(instr):
    out = shape_elems(instr.shape)
    ksp = 1
    m = re.search(r"size=([\dx]+)", instr.attrs)
    if m:
        ksp = math.prod(int(x) for x in m.group(1).split("x"))
    cin = 1
    dl = re.search(r"dim_labels=(\S+?)(?:,|$|\s)", instr.attrs)
    if dl and len(instr.operand_shapes) >= 2:
        rhs = _ARRAY_RE.search(instr.operand_shapes[1])
        labels = dl.group(1).split("_")
        if rhs and len(labels) >= 2:
            rdims = [int(d) for d in rhs.group(2).split(",") if d]
            rlab = labels[1].split("-")[0]
            if "i" in rlab and rlab.index("i") < len(rdims):
                # rhs input-feature dim is already per-group
                cin = rdims[rlab.index("i")]
    return 2 * out * ksp * cin


def instr_cost(instr, module, _seen=None):
    """(flops, bytes) for one instruction. Called computations price
    recursively (cycle-guarded); bytes stay the instruction's own
    operand/output footprint."""
    nbytes = shape_bytes(instr.shape) + sum(
        shape_bytes(s) for s in instr.operand_shapes)
    op = instr.opcode
    if op in TRIVIAL_OPCODES:
        return 0, 0
    if op in _COMMS_OPCODES:
        return 0, nbytes
    if op == "dot":
        return _dot_flops(instr), nbytes
    if op == "convolution":
        return _conv_flops(instr), nbytes
    if op in ("fusion", "call", "while", "conditional", "map",
              "async-start", "custom-call"):
        flops = 0
        seen = _seen if _seen is not None else set()
        for cname in instr.calls:
            if cname in seen:
                continue
            seen.add(cname)
            for sub in module.computations.get(cname, ()):
                f, _ = instr_cost(sub, module, _seen=seen)
                flops += f
        return flops, nbytes
    if op in ("reduce", "reduce-window", "select-and-scatter", "sort",
              "scatter", "gather", "cholesky", "triangular-solve",
              "rng", "rng-bit-generator"):
        touched = sum(shape_elems(s) for s in instr.operand_shapes) or \
            shape_elems(instr.shape)
        if op == "reduce-window":
            m = re.search(r"size=([\dx]+)", instr.attrs)
            if m:
                touched = shape_elems(instr.shape) * math.prod(
                    int(x) for x in m.group(1).split("x"))
        return touched, nbytes
    if op in _ELEMENTWISE or op.endswith("-convert"):
        return shape_elems(instr.shape), nbytes
    # movement-shaped leftovers (copy, transpose, reshape, slice,
    # broadcast, concatenate, pad, dynamic-slice, ...): bytes only
    return 0, nbytes


def is_comms(instr):
    return instr.opcode in _COMMS_OPCODES
