"""Capture harness: measured per-op time joined against the ledger.

The loop every perf investigation needs, packaged:

1. compile the step function and price it (``ledger.from_compiled``),
2. run N steps under ``jax.profiler`` with a telemetry
   ``step_boundary`` per step (so ``mx_step_time_seconds`` accrues the
   wall-clock truth the attribution must reconcile against),
3. parse the xplane artifact, join measured per-op device time onto
   the ledger rows, classify each op on the measured roofline, and
4. report reconciliation: the union of attributed device intervals
   must cover >= 90% of the telemetry step wall-time, or the table is
   lying about where the time goes (``reconciled`` carries the ratio;
   callers/tests gate on it).

Works identically on the CPU backend (per-thunk tracemes) and on TPU
("XLA Ops" lines) — the join key is HLO instruction names either way.
"""
from __future__ import annotations

import os
import tempfile
import time

from . import hlo, ledger, xplane

ATTRIBUTION_VERSION = 1


def profile_dir_default():
    return os.environ.get("MXTPU_PROFILE_DIR") or os.path.join(
        tempfile.gettempdir(), "mxtpu_profile")


def _telemetry_step_total():
    """(sum_s, count) of completed telemetry step intervals, 0s when
    telemetry is disabled or absent."""
    try:
        from .. import telemetry
        snap = telemetry.snapshot()["metrics"]
        fam = snap.get("mx_step_time_seconds", {"series": []})
        for s in fam["series"]:
            return float(s.get("sum", 0.0)), int(s.get("count", 0))
    except Exception:  # noqa: BLE001 — reconciliation degrades to wall
        pass
    return 0.0, 0


def attribution_run(step_fn, args=(), steps=3, profile_dir=None,
                    items_per_step=None, source="profiling",
                    warmup=True):
    """Run ``step_fn(*args)`` ``steps`` times under capture and return
    the joined attribution document.

    ``step_fn`` must be a jitted callable (``jax.jit`` output) of
    device arrays; its result is block_until_ready'd per step so each
    telemetry interval is a true device step. When the step returns
    donated updates ``(new_args..., aux)`` matching ``args`` in
    prefix, pass ``args`` positionally and the harness threads them.
    """
    import jax

    from ..telemetry import step as _tstep

    if profile_dir is None:
        profile_dir = os.path.join(
            profile_dir_default(), "attrib_%d" % os.getpid())
    compiled = step_fn.lower(*args).compile() \
        if hasattr(step_fn, "lower") else None
    if compiled is None:
        step_fn = jax.jit(step_fn)
        compiled = step_fn.lower(*args).compile()
    doc = ledger.from_compiled(compiled)

    def _ready(out):
        leaves = jax.tree_util.tree_leaves(out)
        for leaf in leaves:
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
        return out

    cur = tuple(args)
    if warmup:
        out = _ready(step_fn(*cur))
        cur = _thread(cur, out)
    # drop any interval state an earlier training loop left open: the
    # harness's first step_boundary must OPEN a fresh interval, not
    # close a stale one into the measurement window
    _tstep.reset()
    base_sum, base_count = _telemetry_step_total()
    wall0 = time.perf_counter()
    jax.profiler.start_trace(profile_dir)
    try:
        _tstep.step_boundary(source=source)  # opens the first interval
        for _ in range(steps):
            out = _ready(step_fn(*cur))
            cur = _thread(cur, out)
            _tstep.step_boundary(source=source)
    finally:
        jax.profiler.stop_trace()
    wall_s = time.perf_counter() - wall0
    tele_sum, tele_count = _telemetry_step_total()
    step_wall_s = tele_sum - base_sum
    step_count = tele_count - base_count
    if step_count <= 0 or step_wall_s <= 0:
        # telemetry disabled: fall back to the harness wall clock so
        # the reconciliation ratio still means something
        step_wall_s = wall_s
        step_count = steps
    planes = xplane.load_xspace(profile_dir)
    return join(doc, planes, step_wall_s=step_wall_s,
                steps=step_count, items_per_step=items_per_step,
                profile_dir=profile_dir)


def _thread(cur, out):
    """Thread donated outputs back as next-step inputs when the step
    returns a tuple prefix-shaped like its inputs (bench-style
    ``step(params, moms, ...) -> (params, moms, loss)``)."""
    if not isinstance(out, tuple) or not cur:
        return cur
    n = min(len(out), len(cur))
    k = 0
    try:
        while k < n and _treedef_like(out[k], cur[k]):
            k += 1
    except Exception:  # noqa: BLE001 — threading is best-effort
        return cur
    return tuple(out[:k]) + tuple(cur[k:])


def _treedef_like(a, b):
    import jax
    if jax.tree_util.tree_structure(a) != \
            jax.tree_util.tree_structure(b):
        return False
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        if getattr(la, "shape", None) != getattr(lb, "shape", None) \
                or getattr(la, "dtype", None) != getattr(lb, "dtype",
                                                         None):
            return False
    return True


def join(ledger_doc, planes, step_wall_s=None, steps=None,
         items_per_step=None, profile_dir=None):
    """Join measured xplane op times onto a ledger document.

    The join key is ENTRY instruction names: a ``while`` body's inner
    thunks and a call's fused computation re-emit events under inner
    names that match no entry row, so their time reaches the table
    only through the enclosing entry-level event — one nanosecond,
    one row. Interval union (not the per-row sum) is what reconciles
    against step wall-time, so concurrently-executing thunks don't
    overcount either.
    """
    mod_names = {row["instr"] for row in ledger_doc["rows"]}
    measured = xplane.measure_ops(planes, mod_names)
    per_step = float(steps) if steps else 1.0
    peak_fs = ledger_doc["peak_tflops"] * 1e12
    peak_bs = ledger_doc["peak_hbm_gbs"] * 1e9
    rows = []
    attributed_s = 0.0
    for row in ledger_doc["rows"]:
        m = measured["ops"].get(row["instr"])
        out = dict(row)
        if m:
            self_s = m["self_s"] / per_step
            out["measured_s"] = self_s
            out["measured_count"] = m["count"]
            attributed_s += self_s
            if self_s > 0:
                achieved_fs = row["flops"] / self_s
                achieved_bs = row["bytes"] / self_s
                out["mfu"] = round(achieved_fs / peak_fs, 4)
                out["hbm_util"] = round(achieved_bs / peak_bs, 4)
                out["bound_measured"] = (
                    "comms" if row["bound"] == "comms" else
                    "compute" if achieved_fs / peak_fs
                    >= achieved_bs / peak_bs else "hbm")
        rows.append(out)
    rows.sort(key=lambda r: -(r.get("measured_s", 0.0)
                              or r.get("est_s", 0.0)))
    step_wall = step_wall_s if step_wall_s else None
    covered_per_step = measured["covered_s"] / per_step
    window_per_step = measured["window_s"] / per_step
    unattributed = max(window_per_step - covered_per_step, 0.0)
    doc = dict(ledger_doc)
    doc["kind"] = "mfu_attribution"
    doc["version"] = ATTRIBUTION_VERSION
    doc["rows"] = rows
    by_op = _merge_measured(doc, rows)
    if unattributed > 0:
        # device busy time no named event claims (on CPU: Eigen
        # pool-offloaded conv work) — an explicit row, never silence
        by_op.append({
            "op": "_unattributed", "instrs": 0, "flops": 0, "bytes": 0,
            "est_s": 0.0, "bound": "?",
            "measured_s": round(unattributed, 6)})
        by_op.sort(key=lambda g: -(g.get("measured_s", 0.0)
                                   or g.get("est_s", 0.0)))
    doc["by_op"] = by_op
    doc["measured"] = {
        "steps": steps,
        "matched_events": measured["matched_events"],
        "named_s_per_step": round(covered_per_step, 6),
        "attributed_s_per_step": round(attributed_s, 6),
        "device_window_s_per_step": round(window_per_step, 6),
        "unattributed_s_per_step": round(unattributed, 6),
    }
    if profile_dir:
        doc["profile_dir"] = profile_dir
    if step_wall:
        per_step_wall = step_wall / per_step
        doc["reconciliation"] = {
            "step_wall_s": round(per_step_wall, 6),
            # the table's total claim on the step: named rows plus the
            # explicit _unattributed row = the device-busy window
            "attributed_s": round(window_per_step, 6),
            "named_s": round(covered_per_step, 6),
            "ratio": round(window_per_step / per_step_wall, 4)
            if per_step_wall > 0 else 0.0,
            "idle_s": round(max(per_step_wall - window_per_step, 0.0),
                            6),
        }
        doc["reconciled"] = doc["reconciliation"]["ratio"] >= 0.9
        flops_step = doc["totals"]["flops"]
        doc["mfu"] = round(flops_step / (per_step_wall * peak_fs), 4)
        if items_per_step:
            doc["items_per_s"] = round(
                items_per_step / per_step_wall, 2)
    return doc


def _merge_measured(doc, rows):
    """by_op regrouped with measured seconds + measured MFU."""
    groups = ledger.group_by_op(
        [{k: r[k] for k in ("op", "hlo_op", "flops", "bytes", "est_s",
                            "bound") if k in r} | (
            {"rule": r["rule"]} if r.get("rule") else {})
         for r in rows],
        doc["peak_tflops"], doc["peak_hbm_gbs"])
    meas = {}
    for r in rows:
        if "measured_s" in r:
            key = r.get("op") or r["hlo_op"]
            meas[key] = meas.get(key, 0.0) + r["measured_s"]
    peak_fs = doc["peak_tflops"] * 1e12
    for g in groups:
        if g["op"] in meas:
            g["measured_s"] = round(meas[g["op"]], 6)
            if g["measured_s"] > 0:
                g["mfu"] = round(
                    g["flops"] / g["measured_s"] / peak_fs, 4)
    groups.sort(key=lambda g: -(g.get("measured_s", 0.0)
                                or g.get("est_s", 0.0)))
    return groups


def analyze_dir(profile_dir, compiled=None, hlo_text=None,
                step_wall_s=None, steps=None, **kwargs):
    """Join an existing capture directory against a ledger built from
    ``compiled`` (or raw ``hlo_text``)."""
    if compiled is not None:
        doc = ledger.from_compiled(compiled, **kwargs)
    elif hlo_text is not None:
        doc = ledger.build_ledger(hlo_text, **kwargs)
    else:
        raise ValueError("analyze_dir needs compiled= or hlo_text=")
    planes = xplane.load_xspace(profile_dir)
    return join(doc, planes, step_wall_s=step_wall_s, steps=steps,
                profile_dir=profile_dir)
