"""The XLA cost ledger: per-op/per-fusion FLOPs, bytes, roofline.

Builds a ranked attribution table from a compiled executable's
optimized HLO (``hlo.py`` prices each instruction analytically) and
keys every row back to a *framework* op name:

- jax stamps each HLO instruction with an ``op_name`` metadata path
  ("jit(forward)/.../jit(convolution)/conv_general_dilated"). Ops
  dispatched through ``ops/registry.OpDef`` ride their own inner
  ``jit(<fn name>)`` scope, and the graph executor wraps each node in
  ``jax.named_scope("mx.<OpName>")`` — both survive XLA optimization,
  so the rightmost recognizable component names the framework op.
- A fused cluster created by a subgraph property (``_sg_xla_conv``
  from ``subgraph/xla_fuse.py``) attributes to that property's rule —
  the TVM/Relay move (PAPERS.md): cost lands on the fusion decision
  that produced the cluster, so "did this fusion rule pay?" is a
  ledger diff, not a guess.

Every row gets a roofline classification against
``MXTPU_PEAK_TFLOPS`` / ``MXTPU_PEAK_HBM_GBS``: ``compute`` when
flops/peak dominates the estimated time, ``hbm`` when bytes/bandwidth
does, ``comms`` for collectives, ``trivial`` for costless plumbing.

The ledger document is plain JSON (versioned) so ``tools/
mfu_report.py`` renders and diffs it standalone, and ``bench.py``
embeds its top-10 in every artifact — success, stale, or failure.
"""
from __future__ import annotations

import json
import os
import re

from . import hlo

LEDGER_VERSION = 1

# MXTPU_PEAK_TFLOPS default matches bench.py (v5e bf16); HBM GB/s
# default is the v5e figure — both overridable per chip
_DEF_PEAK_TFLOPS = 197.0
_DEF_PEAK_HBM_GBS = 819.0

_JIT_SCOPE = re.compile(r"^jit\(([^)]*)\)$")


def _peaks(peak_tflops=None, peak_hbm_gbs=None):
    if peak_tflops is None:
        peak_tflops = float(os.environ.get("MXTPU_PEAK_TFLOPS",
                                           _DEF_PEAK_TFLOPS))
    if peak_hbm_gbs is None:
        peak_hbm_gbs = float(os.environ.get("MXTPU_PEAK_HBM_GBS",
                                            _DEF_PEAK_HBM_GBS))
    return peak_tflops, peak_hbm_gbs


def framework_fn_map():
    """{python fn name: canonical op name} over the live op registry
    (empty when the framework isn't importable — attribution then
    falls back to raw jax primitive names)."""
    try:
        from ..ops import registry as _reg
    except Exception:  # noqa: BLE001 — standalone tool loads
        return {}
    return _reg.fn_name_map()


def fusion_rule_map():
    """{fused op name: subgraph backend rule} from the live subgraph
    property registry (e.g. {"_sg_xla_conv": "XLA/conv_bn_add_relu"})."""
    try:
        from ..subgraph import partition as _part
    except Exception:  # noqa: BLE001 — standalone tool loads
        return {}
    out = {}
    for backend, prop in _part.registered_properties().items():
        # a backend may be one property or a whole rule fleet; either
        # way every rule's fused op name attributes to "backend/rule"
        props = prop if isinstance(prop, (list, tuple)) else (prop,)
        for p in props:
            rule = getattr(p, "rule_name", None)
            out[p.op_name] = ("%s/%s" % (backend, rule) if rule
                             else backend)
    return out


def attribute_op_name(op_name_path, fn_map):
    """Framework op for a jax metadata ``op_name`` path: the rightmost
    ``mx.<Name>`` named-scope or ``jit(<registered fn>)`` component,
    else the leaf primitive name."""
    if not op_name_path:
        return None
    parts = op_name_path.split("/")
    for part in reversed(parts):
        if part.startswith("mx."):
            return part[3:]
        m = _JIT_SCOPE.match(part)
        if m and m.group(1) in fn_map:
            return fn_map[m.group(1)]
        # an unregistered jit(<fn>) scope deliberately does NOT win
        # over the leaf primitive: any unlisted entry-point name
        # (jit(fwd), jit(predict), ...) would swallow every
        # instruction without an inner scope and collapse the table
        # onto one row
    leaf = parts[-1]
    return leaf.split("[", 1)[0] or None


def build_ledger(hlo_text, peak_tflops=None, peak_hbm_gbs=None,
                 fn_map=None, rule_map=None, module=None):
    """Price an optimized-HLO module into a ledger document."""
    peak_tflops, peak_hbm_gbs = _peaks(peak_tflops, peak_hbm_gbs)
    mod = module if module is not None else hlo.parse_module(hlo_text)
    if fn_map is None:
        fn_map = framework_fn_map()
    if rule_map is None:
        rule_map = fusion_rule_map()
    peak_fs = peak_tflops * 1e12
    peak_bs = peak_hbm_gbs * 1e9
    rows = []
    tot_f = tot_b = tot_t = 0
    for instr in mod.entry_instructions:
        flops, nbytes = hlo.instr_cost(instr, mod)
        if instr.opcode in hlo.TRIVIAL_OPCODES:
            continue
        t_flops = flops / peak_fs
        t_bytes = nbytes / peak_bs
        est_s = max(t_flops, t_bytes)
        if hlo.is_comms(instr):
            bound = "comms"
        elif flops == 0 and nbytes == 0:
            bound = "trivial"
        elif t_flops >= t_bytes:
            bound = "compute"
        else:
            bound = "hbm"
        op = attribute_op_name(instr.op_name, fn_map)
        row = {
            "instr": instr.name,
            "hlo_op": instr.opcode,
            "op": op,
            "flops": flops,
            "bytes": nbytes,
            "est_s": est_s,
            "bound": bound,
        }
        rule = rule_map.get(op)
        if rule:
            row["rule"] = rule
        rows.append(row)
        tot_f += flops
        tot_b += nbytes
        tot_t += est_s
    rows.sort(key=lambda r: -r["est_s"])
    return {
        "version": LEDGER_VERSION,
        "kind": "cost_ledger",
        "module": mod.name,
        "peak_tflops": peak_tflops,
        "peak_hbm_gbs": peak_hbm_gbs,
        "totals": {"flops": tot_f, "bytes": tot_b, "est_s": tot_t,
                   "rows": len(rows)},
        "rows": rows,
        "by_op": group_by_op(rows, peak_tflops, peak_hbm_gbs),
    }


def group_by_op(rows, peak_tflops=None, peak_hbm_gbs=None):
    """Ledger rows re-aggregated on the framework-op attribution; the
    group's roofline bound is recomputed from its summed flops/bytes
    (majority-of-cost, not majority-of-instructions)."""
    peak_tflops, peak_hbm_gbs = _peaks(peak_tflops, peak_hbm_gbs)
    agg = {}
    comms = set()
    for r in rows:
        key = r.get("op") or r["hlo_op"]
        a = agg.setdefault(key, {
            "op": key, "instrs": 0, "flops": 0, "bytes": 0,
            "est_s": 0.0})
        a["instrs"] += 1
        a["flops"] += r["flops"]
        a["bytes"] += r["bytes"]
        a["est_s"] += r["est_s"]
        if r.get("rule"):
            a["rule"] = r["rule"]
        if r["bound"] == "comms":
            comms.add(key)
    out = sorted(agg.values(), key=lambda a: -a["est_s"])
    for a in out:
        if a["op"] in comms:
            a["bound"] = "comms"
        elif a["flops"] == 0 and a["bytes"] == 0:
            a["bound"] = "trivial"
        else:
            a["bound"] = ("compute"
                          if a["flops"] / (peak_tflops * 1e12)
                          >= a["bytes"] / (peak_hbm_gbs * 1e9)
                          else "hbm")
    return out


def from_compiled(compiled, hlo_text=None, **kwargs):
    """Ledger from a ``jax.stages.Compiled`` — folds in XLA's own
    aggregate ``cost_analysis`` as a cross-check. Pass ``hlo_text``/
    ``module=`` to share one serialization/parse with other passes
    over the same executable (bench_ledger prices flops AND memory)."""
    if hlo_text is None:
        hlo_text = compiled.as_text()
    doc = build_ledger(hlo_text, **kwargs)
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        doc["xla_cost_analysis"] = {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        }
        xf = doc["xla_cost_analysis"]["flops"]
        if xf > 0 and doc["totals"]["flops"] > 0:
            doc["flops_vs_xla"] = round(doc["totals"]["flops"] / xf, 4)
    except Exception:  # noqa: BLE001 — cost_analysis is backend-best-effort
        pass
    return doc


def from_fn(fn, *args, **kwargs):
    """Lower+compile ``fn`` on the current backend and price it.
    ``fn`` may already be jitted; plain callables are jitted here."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return from_compiled(jitted.lower(*args).compile(), **kwargs)


def mfu_estimate(doc, items_per_step=None, step_s=None):
    """Cost-model MFU numbers from a ledger document alone.

    - ``mfu_at_roofline``: flops_total / (est_s * peak) — the MFU the
      roofline model says this module could reach if every op hit its
      bound. The honest ceiling a wedged round can still commit.
    - with ``step_s``: ``mfu_measured`` = flops_total / (step_s * peak).
    - with ``items_per_step``: ``gflops_per_item`` for throughput math.
    """
    peak_fs = doc["peak_tflops"] * 1e12
    flops = doc["totals"]["flops"]
    est_s = doc["totals"]["est_s"]
    out = {"flops_total": flops,
           "gflops_total": round(flops / 1e9, 3),
           "est_step_s": round(est_s, 6),
           "mfu_at_roofline": round(flops / (est_s * peak_fs), 4)
           if est_s > 0 else 0.0}
    if items_per_step:
        out["gflops_per_item"] = round(flops / items_per_step / 1e9, 3)
    if step_s:
        out["mfu_measured"] = round(flops / (step_s * peak_fs), 4)
    return out


def summarize(doc, top=10):
    """Bounded summary for embedding in bench artifacts: MFU estimate
    + the top-N by_op rows, short keys, no raw instruction table."""
    est = mfu_estimate(doc)
    rows = []
    tot_t = doc["totals"]["est_s"] or 1e-30
    for a in doc.get("by_op", [])[:top]:
        rows.append({
            "op": a["op"],
            "gflops": round(a["flops"] / 1e9, 3),
            "mb": round(a["bytes"] / 1e6, 3),
            "est_ms": round(a["est_s"] * 1e3, 4),
            "share": round(a["est_s"] / tot_t, 4),
            "bound": a.get("bound", "?"),
        })
    out = {"mfu_at_roofline": est["mfu_at_roofline"],
           "gflops_total": est["gflops_total"],
           "est_step_s": est["est_step_s"],
           "top": rows}
    if "flops_vs_xla" in doc:
        out["flops_vs_xla"] = doc["flops_vs_xla"]
    return out


def diff(before, after):
    """Ranked per-op delta between two ledger (or attribution)
    documents — the mfu_report --diff payload."""
    def index(doc):
        return {a["op"]: a for a in doc.get("by_op", [])}

    ia, ib = index(before), index(after)
    out = []
    for op in sorted(set(ia) | set(ib)):
        a = ia.get(op, {})
        b = ib.get(op, {})
        ta = a.get("measured_s", a.get("est_s", 0.0))
        tb = b.get("measured_s", b.get("est_s", 0.0))
        out.append({
            "op": op,
            "before_s": ta, "after_s": tb, "delta_s": tb - ta,
            "before_flops": a.get("flops", 0),
            "after_flops": b.get("flops", 0),
        })
    out.sort(key=lambda r: -abs(r["delta_s"]))
    return out


def load(path):
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "rows" not in doc or \
            "totals" not in doc:
        raise ValueError("%s is not a ledger document" % path)
    return doc


def dump(doc, path):
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc
