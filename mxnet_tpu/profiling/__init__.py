"""Performance attribution: *where* the chip time goes.

The third observability layer (telemetry = how much, tracing =
why/when): per-op/per-fusion FLOPs, HBM bytes and time, keyed back to
framework op names and fusion rules, reconciled against measured
reality.

- :mod:`~mxnet_tpu.profiling.hlo` — optimized-HLO parser + analytic
  per-instruction cost model (stdlib-only),
- :mod:`~mxnet_tpu.profiling.ledger` — the cost ledger: build, price,
  attribute, summarize, diff,
- :mod:`~mxnet_tpu.profiling.xplane` — ``jax.profiler`` xplane
  protobuf reader (stdlib-only) + measured per-op device time,
- :mod:`~mxnet_tpu.profiling.capture` — run-under-capture harness
  joining measured time onto the ledger with a >= 90% reconciliation
  gate against telemetry ``mx_step_time_seconds``,
- :mod:`~mxnet_tpu.profiling.memory` — the memory axis: static
  liveness ledger over compiled HLO (peak live bytes + ranked buffer
  table), live-array census with role tagging (per device shard), and
  the OOM postmortem artifact,
- :mod:`~mxnet_tpu.profiling.bench_ledger` — the ``python -m``
  subprocess ``bench.py`` uses to compute a CPU cost-model ledger even
  when the TPU tunnel is wedged.

CLI: ``tools/mfu_report.py`` (table / --diff / --capture / --chrome)
and ``tools/memory_report.py`` (table / --diff / --capture / --hlo).
Env: ``MXTPU_PROFILE_ATTRIB``, ``MXTPU_PROFILE_DIR``,
``MXTPU_PEAK_HBM_GBS``, ``MXTPU_MEMORY_CENSUS``,
``MXTPU_OOM_DUMP_PATH`` (+ the existing ``MXTPU_PEAK_TFLOPS``) —
registered in ``libinfo._ENV_VARS``, documented in
``docs/observability.md`` ("MFU accounting & roofline", "Memory
accounting").
"""
from __future__ import annotations

from . import hlo
from . import ledger
from . import xplane
from . import capture
from . import memory
from .capture import analyze_dir, attribution_run
from .ledger import build_ledger, from_compiled, from_fn, mfu_estimate
from .memory import (build_memory_ledger, live_census, tag_role,
                     tag_tree, maybe_oom_postmortem, oom_postmortem)

__all__ = ["hlo", "ledger", "xplane", "capture", "memory",
           "build_ledger", "from_compiled", "from_fn", "mfu_estimate",
           "analyze_dir", "attribution_run", "build_memory_ledger",
           "live_census", "tag_role", "tag_tree",
           "maybe_oom_postmortem", "oom_postmortem"]
