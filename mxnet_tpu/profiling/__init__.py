"""Performance attribution: *where* the chip time goes.

The third observability layer (telemetry = how much, tracing =
why/when): per-op/per-fusion FLOPs, HBM bytes and time, keyed back to
framework op names and fusion rules, reconciled against measured
reality.

- :mod:`~mxnet_tpu.profiling.hlo` — optimized-HLO parser + analytic
  per-instruction cost model (stdlib-only),
- :mod:`~mxnet_tpu.profiling.ledger` — the cost ledger: build, price,
  attribute, summarize, diff,
- :mod:`~mxnet_tpu.profiling.xplane` — ``jax.profiler`` xplane
  protobuf reader (stdlib-only) + measured per-op device time,
- :mod:`~mxnet_tpu.profiling.capture` — run-under-capture harness
  joining measured time onto the ledger with a >= 90% reconciliation
  gate against telemetry ``mx_step_time_seconds``,
- :mod:`~mxnet_tpu.profiling.memory` — the memory axis: static
  liveness ledger over compiled HLO (peak live bytes + ranked buffer
  table), live-array census with role tagging (per device shard), and
  the OOM postmortem artifact,
- :mod:`~mxnet_tpu.profiling.bench_ledger` — the ``python -m``
  subprocess ``bench.py`` uses to compute a CPU cost-model ledger even
  when the TPU tunnel is wedged,
- :mod:`~mxnet_tpu.profiling.health` — the numerics axis: sync-free
  nonfinite sentry at the framework seams, gradient/update-ratio
  telemetry, loss-anomaly detection, the first-NaN postmortem, and
  drift fingerprints.

CLI: ``tools/mfu_report.py`` (table / --diff / --capture / --chrome),
``tools/memory_report.py`` (table / --diff / --capture / --hlo) and
``tools/health_report.py`` (table / --diff / --postmortem).
Env: ``MXTPU_PROFILE_ATTRIB``, ``MXTPU_PROFILE_DIR``,
``MXTPU_PEAK_HBM_GBS``, ``MXTPU_MEMORY_CENSUS``,
``MXTPU_OOM_DUMP_PATH``, ``MXTPU_HEALTH``, ``MXTPU_HEALTH_DUMP_PATH``,
``MXTPU_HEALTH_NORMS``, ``MXTPU_HEALTH_ANOMALY_Z`` (+ the existing
``MXTPU_PEAK_TFLOPS``) — registered in ``libinfo._ENV_VARS``,
documented in ``docs/observability.md`` ("MFU accounting & roofline",
"Memory accounting", "Model health").
"""
from __future__ import annotations

from . import hlo
from . import ledger
from . import xplane
from . import capture
from . import memory
from . import health
from .capture import analyze_dir, attribution_run
from .ledger import build_ledger, from_compiled, from_fn, mfu_estimate
from .memory import (build_memory_ledger, live_census, tag_role,
                     tag_tree, maybe_oom_postmortem, oom_postmortem)
from .health import (fingerprint_params, nan_postmortem,
                     localize_first_nonfinite, NonfiniteError)

__all__ = ["hlo", "ledger", "xplane", "capture", "memory", "health",
           "build_ledger", "from_compiled", "from_fn", "mfu_estimate",
           "analyze_dir", "attribution_run", "build_memory_ledger",
           "live_census", "tag_role", "tag_tree",
           "maybe_oom_postmortem", "oom_postmortem",
           "fingerprint_params", "nan_postmortem",
           "localize_first_nonfinite", "NonfiniteError"]
