"""Cost-ledger pass for the bench supervisor (``python -m``).

Runs in a throwaway subprocess pinned to the CPU backend, compiles
the bench stage programs there, and writes their cost ledgers to
``MXTPU_LEDGER_OUT`` — so every bench round commits a cost-model MFU
estimate and top-10 op table even when the TPU tunnel never answers
(the r04/r05 artifacts were bare 0.0 with no signal at all).

The output file is written atomically after EVERY completed stage:
the supervisor reads whatever has landed when it needs to emit, and a
deadline kill mid-pass still leaves the finished stages behind.

Stages (``MXTPU_LEDGER_STAGES``, comma-separated):

- ``infer_bf16`` — the headline ``bench.build_forward`` program,
- ``train_bf16`` — the ``bench.build_train`` step (slow compile; runs
  last by default),
- ``tiny``       — a small conv net train step that compiles in
  seconds (the failure-injection test hook).

XLA's optimized HLO is backend-specific, but FLOPs and bytes are
graph properties: the CPU-compiled ledger's *costs* transfer to the
chip; only the fusion boundaries are approximate. The document says
so (``backend`` field).
"""
from __future__ import annotations

import os
import sys
import time


def _tiny_train_step():
    import jax
    import jax.numpy as jnp

    def loss_fn(w1, w2, x):
        y = jax.lax.conv_general_dilated(
            x, w1, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        y = jnp.maximum(y, 0)
        y = jax.lax.conv_general_dilated(
            y, w2, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return jnp.mean(y * y)

    def step(w1, w2, x):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            w1, w2, x)
        return (w1 - 0.01 * grads[0], w2 - 0.01 * grads[1], loss)

    w1 = jnp.zeros((16, 3, 3, 3), jnp.float32)
    w2 = jnp.zeros((16, 16, 3, 3), jnp.float32)
    x = jnp.zeros((8, 3, 32, 32), jnp.float32)
    return jax.jit(step), (w1, w2, x), 8


def _stage_compiled(stage, batch):
    """(compiled, items_per_step) for a bench stage program."""
    import jax
    import jax.numpy as jnp

    import bench

    if stage == "tiny":
        step, args, items = _tiny_train_step()
        return step.lower(*args).compile(), items
    if stage == "infer_bf16":
        fwd, pvals = bench.build_forward(batch)
        data = jnp.zeros((batch, 3, 224, 224), jnp.bfloat16)
        return fwd.lower(pvals, data).compile(), batch
    if stage == "train_bf16":
        step, params, moms = bench.build_train(batch)
        data = jnp.zeros((batch, 3, 224, 224), jnp.bfloat16)
        labels = jnp.zeros((batch,), jnp.int32)
        return step.lower(params, moms, data, labels).compile(), batch
    raise ValueError("unknown ledger stage %r" % (stage,))


def main(argv=None):
    out_path = os.environ.get("MXTPU_LEDGER_OUT") or "bench_ledger.json"
    stages = [s.strip() for s in os.environ.get(
        "MXTPU_LEDGER_STAGES", "infer_bf16,train_bf16").split(",")
        if s.strip()]
    batch = int(os.environ.get("MXTPU_BENCH_BATCH", "128"))

    # repo root (bench.py lives beside mxnet_tpu/) must be importable
    # when launched via `python -m` from elsewhere
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo not in sys.path:
        sys.path.insert(0, repo)

    from mxnet_tpu.profiling import hlo as _hlo
    from mxnet_tpu.profiling import ledger
    from mxnet_tpu.profiling import memory as _memory

    doc = {"version": 1, "kind": "bench_cost_ledger",
           "backend": "cpu", "batch": batch, "stages": {}}

    def flush():
        ledger.dump(doc, out_path)

    rc = 0
    for stage in stages:
        stage_t0 = time.time()
        try:
            compiled, items = _stage_compiled(stage, batch)
            # serialize + parse the (megabytes of) optimized HLO once;
            # the flop and memory passes share it
            txt = compiled.as_text()
            mod = _hlo.parse_module(txt)
            led = ledger.from_compiled(compiled, hlo_text=txt,
                                       module=mod)
            summary = ledger.summarize(led)
            summary["gflops_per_item"] = round(
                led["totals"]["flops"] / items / 1e9, 3)
            summary["compile_s"] = round(time.time() - stage_t0, 1)
            try:
                # bounded memory summary (peak live bytes + top-3
                # buffers): rides the same stage record into every
                # bench artifact — success, stale, or failure
                summary["memory"] = _memory.summarize(
                    _memory.from_compiled(compiled, hlo_text=txt,
                                          module=mod), top=3)
            except Exception as e:  # noqa: BLE001 — memory must not
                summary["memory"] = {   # sink the flop ledger
                    "stage_error": repr(e)[:120]}
            doc["stages"][stage] = summary
        except Exception as e:  # noqa: BLE001 — a failed stage must not
            # "stage_error", not "error": bench.py line-level gates
            # treat a top-level '"error"' as a failed measurement
            doc["stages"][stage] = {"stage_error": repr(e)[:300]}
            rc = 1
        flush()
    return rc


if __name__ == "__main__":
    sys.exit(main())
