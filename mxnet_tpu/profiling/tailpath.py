"""Per-request critical-path attribution: why was THIS request slow?

The goodput plane answers "where did the fleet's device-seconds go";
this plane answers the per-request question that ROADMAP item 1
(disaggregated prefill/decode) will be gated on: for one slow request,
which seam ate its wall clock. The serving schedulers (GenLane and the
one-shot ModelQueue/Replica path) stamp typed decision events into the
span layer as attributes — admission verdict + queue-wait cause,
batch-formation hold, bucket/padding share, per-decode-step
prefill-interleave stall, KV reserve waits, replica requeues, lending
reclaim pauses — and the joiner here reconstructs each request's
timeline from its span tree plus those events, binning every
wall-clock nanosecond into a CLOSED blame taxonomy:

======================  =====================================================
``queue_wait``          admission backlog: submit → admitted, minus the
                        causes billed below
``kv_wait``             admission blocked on KV block reserve (the head
                        request could not cover its worst-case budget)
``batch_hold``          one-shot coalescing hold (the ``max_wait`` window
                        spent fishing for batch-mates)
``prefill_compute``     own prompt prefill execution (real-token share)
``prefill_interleave``  OTHER requests' admission work (prefill/replay/
                        migrate landing) holding this request's decode step
``decode_compute``      decode-step execution (real-row share)
``padding_tax``         bucket padding share of prefill/decode/execute
``sched_overhead``      host scheduler bookkeeping not otherwise blamed
                        (batch forming, stacking, emit loops)
``execute``             one-shot batch execution (real-row share)
``reply``               execution end → reply delivered
``requeue``             replica drain/requeue: time lost to a failed
                        attempt before redistribution
``recovery``            decode failover (migrate/replay) after lane loss
``reclaim_pause``       the recovery was caused by a lending reclaim /
                        planned drain (``cause`` says so)
``_unattributed``       residual — the conservation check bounds it
======================  =====================================================

Conservation is the goodput doctrine applied to latency: per request,
attributed bins must sum to the measured e2e wall (root span duration)
within tolerance, and consumers (``perf_gate --tail``) RECOMPUTE that
from the raw numbers — never trusting the artifact's own flag. The
windowed aggregator keeps the last N completed requests, takes the
slowest decile, ranks tail drivers by blamed seconds, publishes
``mx_tail_*`` metric families, and dumps a versioned ``tail/v1``
artifact (``tools/tail_report.py`` renders/diffs it).

Everything here is span/dict arithmetic — no device handles, no syncs
(the MXL002 scope covers the join/ingest/collect paths; the emission
seams in the schedulers stay on their own hot-path scope).

Knobs: ``MXTPU_TAIL_ENABLE`` (default on), ``MXTPU_TAIL_WINDOW``
(completed requests retained, default 512), ``MXTPU_TAIL_SLOW_FRAC``
(slow-cohort fraction, default 0.1), ``MXTPU_TAIL_ARTIFACT``
(auto-dump path for :func:`dump`, default unset).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from ..base import get_env
from ..telemetry import metrics as _tm

TAIL_KIND = "tail/v1"
TAIL_VERSION = 1

BINS = (
    "queue_wait", "kv_wait", "batch_hold",
    "prefill_compute", "prefill_interleave",
    "decode_compute", "padding_tax", "sched_overhead",
    "execute", "reply", "requeue",
    "recovery", "reclaim_pause",
    "_unattributed",
)

# span names whose trees the joiner understands
GENERATE_ROOT = "serving.generate"
ONESHOT_ROOT = "serving.request"

_met = _tm.lazy_metrics(lambda reg: {
    "e2e": reg.histogram(
        "mx_tail_e2e_seconds",
        "attributed request end-to-end wall (submit -> reply/last "
        "token)", labelnames=("kind",)),
    "blame": reg.gauge(
        "mx_tail_blame_seconds",
        "blamed wall-seconds per taxonomy bin at the last collect",
        labelnames=("bin", "cohort")),
    "requests": reg.gauge(
        "mx_tail_requests",
        "requests in the attribution window at the last collect",
        labelnames=("cohort",)),
    "conservation": reg.gauge(
        "mx_tail_conservation_fraction",
        "attributed / measured e2e at the last collect (1.0 = every "
        "nanosecond blamed)", labelnames=("cohort",)),
})


def enabled():
    """Whether the tail-attribution plane records (MXTPU_TAIL_ENABLE)."""
    return bool(get_env("MXTPU_TAIL_ENABLE", 1, int))


def _overlap_ns(a_start, a_end, b_start, b_end):
    return max(min(a_end, b_end) - max(a_start, b_start), 0)


def _num(v, default=0):
    return v if isinstance(v, (int, float)) else default


def _zero_bins():
    return {b: 0 for b in BINS}


def _bins_generate(root, children):
    """Blame bins (ns) for one generate request's span tree, or None
    when the tree is incomplete (ring eviction dropped spans — an
    incomplete tree cannot conserve, so it is skipped and counted)."""
    attrs = root.get("attrs") or {}
    new_tokens = int(_num(attrs.get("new_tokens")))
    prefill = None
    tokens = []
    recovers = []
    for s in children:
        n = s.get("name")
        if n == "generate.prefill":
            prefill = s
        elif n == "generate.token":
            tokens.append(s)
        elif n == "generate.recover":
            recovers.append(s)
    if prefill is None or len(tokens) != new_tokens or not tokens:
        return None
    tokens.sort(key=lambda s: _num((s.get("attrs") or {}).get("index")))
    bins = _zero_bins()
    e2e = _num(root.get("dur_ns"))

    # -- prefill phase: submit -> first token ---------------------------------
    pa = prefill.get("attrs") or {}
    p_dur = _num(prefill.get("dur_ns"))
    q = min(max(int(_num(pa.get("queue_ns"))), 0), p_dur)
    kv = min(max(int(_num(pa.get("kv_wait_ns"))), 0), q)
    bins["kv_wait"] += kv
    bins["queue_wait"] += q - kv
    ex = min(max(int(_num(pa.get("exec_ns"))), 0), p_dur - q)
    plen = max(int(_num(pa.get("prompt_tokens"), 1)), 1)
    tpad = max(int(_num(pa.get("pad_tokens"), plen)), plen)
    pad_frac = (tpad - plen) / tpad
    bins["prefill_compute"] += int(ex * (1.0 - pad_frac))
    bins["padding_tax"] += int(ex * pad_frac)
    bins["sched_overhead"] += max(p_dur - q - ex, 0)

    # -- decode phase: token i-1 emit -> token i emit -------------------------
    rec_iv = [(r["start_ns"], r["start_ns"] + _num(r.get("dur_ns")),
               str((r.get("attrs") or {}).get("cause") or ""))
              for r in recovers]
    prev_end = tokens[0]["start_ns"] + _num(tokens[0].get("dur_ns"))
    for tok in tokens[1:]:
        ta = tok.get("attrs") or {}
        t_start = tok["start_ns"]
        t_end = t_start + _num(tok.get("dur_ns"))
        interval = t_end - prev_end
        if interval <= 0:
            prev_end = max(prev_end, t_end)
            continue
        step = min(max(t_end - t_start, 0), interval)
        # recovery pauses overlapping this inter-token gap (the step
        # itself is never a recovery — clip so nothing double-bills)
        rec = rec_rcl = 0
        for r0, r1, cause in rec_iv:
            ov = _overlap_ns(prev_end, t_end, r0, r1)
            if "reclaim" in cause or "retire" in cause or \
                    "drain" in cause:
                rec_rcl += ov
            else:
                rec += ov
        spare = max(interval - step, 0)
        rec = min(rec, spare)
        rec_rcl = min(rec_rcl, spare - rec)
        # the interleave stamp can include the request's OWN admission
        # work (it was measured lane-wide); the clip to the actual gap
        # keeps attribution conservative
        inter = min(max(int(_num(ta.get("interleave_ns"))), 0),
                    spare - rec - rec_rcl)
        rows = max(int(_num(ta.get("rows"), 1)), 1)
        bucket = max(int(_num(ta.get("bucket"), rows)), rows)
        pad_frac = (bucket - rows) / bucket
        bins["decode_compute"] += int(step * (1.0 - pad_frac))
        bins["padding_tax"] += int(step * pad_frac)
        bins["recovery"] += rec
        bins["reclaim_pause"] += rec_rcl
        bins["prefill_interleave"] += inter
        bins["sched_overhead"] += max(
            interval - step - rec - rec_rcl - inter, 0)
        prev_end = t_end
    attributed = sum(bins.values())
    bins["_unattributed"] = max(e2e - attributed, 0)
    return bins, e2e


def _bins_oneshot(root, children):
    """Blame bins (ns) for one one-shot request's span tree."""
    by_name = {}
    for s in children:
        by_name.setdefault(s.get("name"), s)
    q_span = by_name.get("serving.queue")
    x_span = by_name.get("serving.execute")
    if q_span is None or x_span is None:
        return None
    bins = _zero_bins()
    e2e = _num(root.get("dur_ns"))
    qa = q_span.get("attrs") or {}
    q = _num(q_span.get("dur_ns"))
    hold = min(max(int(_num(qa.get("hold_ns"))), 0), q)
    requeue = min(max(int(_num(qa.get("requeue_ns"))), 0), q - hold)
    bins["batch_hold"] += hold
    bins["requeue"] += requeue
    bins["queue_wait"] += q - hold - requeue
    b_span = by_name.get("serving.batch")
    if b_span is not None:
        bins["sched_overhead"] += _num(b_span.get("dur_ns"))
    xa = x_span.get("attrs") or {}
    ex = _num(x_span.get("dur_ns"))
    rows = max(int(_num(xa.get("rows"), 1)), 1)
    bucket = max(int(_num(xa.get("bucket"), rows)), rows)
    pad_frac = (bucket - rows) / bucket
    bins["execute"] += int(ex * (1.0 - pad_frac))
    bins["padding_tax"] += int(ex * pad_frac)
    r_span = by_name.get("serving.reply")
    if r_span is not None:
        bins["reply"] += _num(r_span.get("dur_ns"))
    attributed = sum(bins.values())
    bins["_unattributed"] = max(e2e - attributed, 0)
    return bins, e2e


def attribute_request(root, children):
    """One request's attribution record from its root span + direct
    children, or None when the tree is incomplete. ``bins`` are ns and
    sum (with ``_unattributed``) to >= the measured e2e; conservation
    is judged by the aggregator/gate, not here."""
    name = root.get("name")
    if name == GENERATE_ROOT:
        out = _bins_generate(root, children)
        kind = "generate"
    elif name == ONESHOT_ROOT:
        out = _bins_oneshot(root, children)
        kind = "oneshot"
    else:
        return None
    if out is None:
        return None
    bins, e2e = out
    attrs = root.get("attrs") or {}
    return {
        "kind": kind,
        "model": attrs.get("model"),
        "trace": root.get("trace"),
        "start_ns": root.get("start_ns"),
        "e2e_ns": e2e,
        "bins": bins,
        "queue_cause": attrs.get("queue_cause"),
    }


def join_spans(spans, t0_ns=None, t1_ns=None):
    """Attribution records for every complete request tree in a
    ``tracing.spans_snapshot()`` list whose root STARTS inside
    [t0_ns, t1_ns) (None = unbounded). Returns ``(records,
    skipped_incomplete)`` — a root whose children were evicted from
    the ring cannot conserve and is counted instead of half-blamed."""
    roots = []
    kids = {}
    for s in spans:
        if s.get("name") in (GENERATE_ROOT, ONESHOT_ROOT):
            st = s.get("start_ns", 0)
            if t0_ns is not None and st < t0_ns:
                continue
            if t1_ns is not None and st >= t1_ns:
                continue
            roots.append(s)
        kids.setdefault((s.get("trace"), s.get("parent")), []).append(s)
    records = []
    skipped = 0
    for root in roots:
        children = kids.get((root.get("trace"), root.get("span")), [])
        rec = attribute_request(root, children)
        if rec is None:
            skipped += 1
        else:
            records.append(rec)
    return records, skipped


class TailAggregator:
    """Windowed slow-cohort attribution: keep the last ``window``
    completed requests, rank the slowest ``slow_frac`` cohort's blame
    bins, publish ``mx_tail_*`` gauges at :meth:`collect`."""

    def __init__(self, window=None, slow_frac=None):
        if window is None:
            window = get_env("MXTPU_TAIL_WINDOW", 512, int)
        if slow_frac is None:
            slow_frac = get_env("MXTPU_TAIL_SLOW_FRAC", 0.1, float)
        self.window = max(int(window), 8)
        self.slow_frac = min(max(float(slow_frac), 0.01), 1.0)
        self._lock = threading.Lock()
        self._records = deque(maxlen=self.window)
        self._skipped = 0
        self._stages = {}

    def add(self, rec, stage=None):
        """Record one completed request's attribution (sync-free:
        deque append + one histogram observe)."""
        with self._lock:
            self._records.append(rec)
            if stage:
                self._stages[stage] = self._stages.get(stage, 0) + 1
        _met()["e2e"].labels(kind=rec.get("kind") or "?").observe(
            rec.get("e2e_ns", 0) / 1e9)

    def ingest_spans(self, spans, stage=None, t0_ns=None, t1_ns=None):
        """Join a span snapshot and add every complete request tree;
        returns the number of records added."""
        records, skipped = join_spans(spans, t0_ns=t0_ns, t1_ns=t1_ns)
        with self._lock:
            self._skipped += skipped
        for rec in records:
            self.add(rec, stage=stage)
        return len(records)

    def collect(self, tolerance=0.10, provenance=None):
        """Build the versioned ``tail/v1`` artifact and publish the
        ``mx_tail_*`` gauges. Conservation (per cohort): attributed
        bins (minus the residual) over measured e2e — the gate
        recomputes the same quotient from the raw numbers."""
        with self._lock:
            records = list(self._records)
            skipped = self._skipped
            stages = dict(self._stages)
        records.sort(key=lambda r: -r.get("e2e_ns", 0))
        n = len(records)
        k = max(int(round(n * self.slow_frac)), 1) if n else 0
        slow = records[:k]

        def _cohort(rs):
            bins = {b: 0.0 for b in BINS}
            e2e = 0.0
            for r in rs:
                e2e += r.get("e2e_ns", 0) / 1e9
                for b, v in (r.get("bins") or {}).items():
                    if b in bins:
                        bins[b] += v / 1e9
            attributed = sum(v for b, v in bins.items()
                             if b != "_unattributed")
            return bins, e2e, attributed

        all_bins, all_e2e, all_attr = _cohort(records)
        slow_bins, slow_e2e, slow_attr = _cohort(slow)
        drivers = sorted(
            ({"bin": b, "blamed_s": round(v, 6),
              "share": round(v / slow_e2e, 4) if slow_e2e else 0.0}
             for b, v in slow_bins.items()
             if v > 0 and b != "_unattributed"),
            key=lambda d: -d["blamed_s"])
        unattr_frac = (slow_bins["_unattributed"] / slow_e2e) \
            if slow_e2e else 0.0
        conserved = bool(
            slow_e2e > 0
            and abs(slow_attr + slow_bins["_unattributed"] - slow_e2e)
            <= tolerance * slow_e2e
            and unattr_frac <= tolerance)
        doc = {
            "tool": "tailpath",
            "kind": TAIL_KIND,
            "version": TAIL_VERSION,
            "created": time.time(),
            "taxonomy": list(BINS),
            "window": {
                "requests": n,
                "capacity": self.window,
                "slow_frac": self.slow_frac,
                "slow_requests": k,
                "skipped_incomplete": skipped,
            },
            "stages": {s: {"requests": c}
                       for s, c in sorted(stages.items())},
            "bins": {b: round(v, 6) for b, v in all_bins.items()},
            "slow": {
                "requests": k,
                "e2e_s": round(slow_e2e, 6),
                "bins": {b: round(v, 6) for b, v in slow_bins.items()},
                "drivers": drivers,
            },
            "conservation": {
                "tolerance": tolerance,
                "e2e_s": round(all_e2e, 6),
                "attributed_s": round(all_attr, 6),
                "unattributed_s": round(all_bins["_unattributed"], 6),
                "slow_e2e_s": round(slow_e2e, 6),
                "slow_attributed_s": round(slow_attr, 6),
                "slow_unattributed_s":
                    round(slow_bins["_unattributed"], 6),
                "fraction": round(all_attr / all_e2e, 4)
                    if all_e2e else 0.0,
                "slow_fraction": round(slow_attr / slow_e2e, 4)
                    if slow_e2e else 0.0,
                "conserved": conserved,
            },
            "slowest": [
                {"e2e_ms": round(r.get("e2e_ns", 0) / 1e6, 3),
                 "kind": r.get("kind"),
                 "model": r.get("model"),
                 "queue_cause": r.get("queue_cause"),
                 "top_bin": max(
                     (b for b in BINS if b != "_unattributed"),
                     key=lambda b: (r.get("bins") or {}).get(b, 0)),
                 "bins_ms": {
                     b: round(v / 1e6, 3)
                     for b, v in sorted((r.get("bins") or {}).items())
                     if v > 0}}
                for r in slow[:8]],
        }
        if provenance is not None:
            doc["provenance"] = provenance
        met = _met()
        for cohort, (bins, e2e, attr) in (
                ("all", (all_bins, all_e2e, all_attr)),
                ("slow", (slow_bins, slow_e2e, slow_attr))):
            for b, v in bins.items():
                met["blame"].labels(bin=b, cohort=cohort).set(v)
            met["conservation"].labels(cohort=cohort).set(
                (attr / e2e) if e2e else 0.0)
        met["requests"].labels(cohort="all").set(n)
        met["requests"].labels(cohort="slow").set(k)
        return doc


def summary(doc, max_bytes=2048):
    """Bounded, provenance-marked embed for bench artifacts (the
    goodput-summary pattern): slow-cohort drivers + conservation
    verdict, guaranteed under ``max_bytes`` serialized."""
    if not isinstance(doc, dict) or doc.get("kind") != TAIL_KIND:
        return None
    cons = doc.get("conservation", {})
    slow = doc.get("slow", {})
    out = {
        "kind": "tail_summary",
        "source": "profiling.tailpath",
        "requests": doc.get("window", {}).get("requests"),
        "slow_requests": slow.get("requests"),
        "slow_e2e_s": slow.get("e2e_s"),
        "slow_fraction": cons.get("slow_fraction"),
        "conserved": cons.get("conserved"),
        "drivers": (slow.get("drivers") or [])[:5],
        "bins": {b: round(float(v), 4)
                 for b, v in sorted((slow.get("bins") or {}).items())},
    }
    # hard bound: drop detail until it fits (provenance keys survive)
    for victim in ("bins", "drivers"):
        if len(json.dumps(out)) <= max_bytes:
            break
        out.pop(victim, None)
    return out


def dump(path, doc):
    """Write the artifact atomically (tmp + rename). ``path=None``
    falls back to ``MXTPU_TAIL_ARTIFACT``; both unset is a no-op (the
    plane records, nobody asked for a file)."""
    if path is None:
        path = get_env("MXTPU_TAIL_ARTIFACT", None, str) or None
    if not path:
        return doc
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(json.dumps(doc, indent=1, sort_keys=True))
    os.replace(tmp, path)
    return doc
