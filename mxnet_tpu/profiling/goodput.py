"""Per-device time accounting: where did the device-seconds go?

The ``DeviceLedger`` conserves LEASE-level device-seconds (every
chip-second belongs to exactly one owner or the free pool); this
module classifies those same seconds into WORK bins from seams the
stack already instruments, then cross-checks the two books — the
census doctrine applied to time instead of bytes. Bins:

====================  =====================================================
``train_compute``     trainer ``step`` spans × dp width (productive)
``serve_prefill``     ``generate.prefill`` + classic ``serving.execute``
                      lane time (productive)
``serve_decode``      ``generate.token`` decode-loop lane time (productive)
``reshape_tax``       ``elastic.reshape`` spans × max(world_from, world_to)
``recovery_tax``      ``generate.recover`` replay/migrate time
``lend_transition``   ``cluster.lend``/``cluster.reclaim`` spans × chips,
                      minus nested reshape time (already billed above)
``idle``              remainder: ledger total − every classified second
====================  =====================================================

Goodput = productive ÷ total. Conservation is recomputed by consumers
(``perf_gate --goodput``) from the raw numbers, never trusted from
the artifact: per-owner classified seconds must fit inside
``DeviceLedger.device_seconds()`` owner totals within tolerance, and
the owner totals themselves must sum to world_size × elapsed.

Everything here is span/dict arithmetic — no device handles, no sync
(MXL002 scope covers the classify/collect paths).
"""
from __future__ import annotations

import json
import os
import time

GOODPUT_KIND = "goodput/v1"
GOODPUT_VERSION = 1

BINS = ("train_compute", "reshape_tax", "serve_prefill",
        "serve_decode", "recovery_tax", "lend_transition", "idle")
PRODUCTIVE_BINS = ("train_compute", "serve_prefill", "serve_decode")
TAX_BINS = ("reshape_tax", "recovery_tax", "lend_transition")

# owner → the bins its ledger seconds may be classified into; the
# cross-check books training's reshape and the lend handshake against
# the training owner (the chips are still training-owned until the
# ledger journal moves them) and serving's recovery replay against
# serving
OWNER_BINS = {
    "training": ("train_compute", "reshape_tax", "lend_transition"),
    "serving": ("serve_prefill", "serve_decode", "recovery_tax"),
}


def _w_step(attrs):
    return max(int(attrs.get("dp", 1) or 1), 1)


def _w_reshape(attrs):
    return max(int(attrs.get("world_from", 1) or 1),
               int(attrs.get("world_to", 1) or 1), 1)


def _w_chips(attrs):
    return max(int(attrs.get("chips", 1) or 1), 1)


def _w_one(attrs):
    return 1


# span name → (bin, device-width from attrs). Children of these spans
# (reshape.quiesce/gather/..., serving.batch under execute) are NOT
# listed — billing a parent and its children would double-count
SPAN_BINS = {
    "step": ("train_compute", _w_step),
    "trainer_step": ("train_compute", _w_step),
    "elastic.reshape": ("reshape_tax", _w_reshape),
    "cluster.lend": ("lend_transition", _w_chips),
    "cluster.reclaim": ("lend_transition", _w_chips),
    "generate.prefill": ("serve_prefill", _w_one),
    "generate.token": ("serve_decode", _w_one),
    "generate.recover": ("recovery_tax", _w_one),
    "serving.execute": ("serve_prefill", _w_one),
}


def _clip(span, t0_ns, t1_ns):
    """The span's [start, end) overlap with the window, in ns."""
    s = span["start_ns"]
    e = s + span.get("dur_ns", 0)
    if t0_ns is not None:
        s = max(s, t0_ns)
    if t1_ns is not None:
        e = min(e, t1_ns)
    return max(e - s, 0)


def _overlap_ns(a_start, a_end, b_start, b_end):
    return max(min(a_end, b_end) - max(a_start, b_start), 0)


def classify_spans(spans, t0_ns=None, t1_ns=None):
    """Classify recorded spans into device-second bins over the
    [t0_ns, t1_ns) window (None = unbounded). Returns
    ``(bins, counts)``: seconds per bin (no ``idle`` — that needs the
    ledger total) and counted spans per span name.

    ``cluster.lend``/``cluster.reclaim`` CONTAIN the
    ``elastic.reshape`` they trigger (the scheduler calls
    ``trainer.reshape`` inside its span); the nested reshape interval
    is subtracted from ``lend_transition`` at the lend span's chip
    width so each wall-second is billed to exactly one bin.
    """
    bins = {b: 0.0 for b in BINS if b != "idle"}
    counts = {}
    reshapes = [(s["start_ns"], s["start_ns"] + s.get("dur_ns", 0))
                for s in spans if s.get("name") == "elastic.reshape"]
    for s in spans:
        rule = SPAN_BINS.get(s.get("name"))
        if rule is None:
            continue
        ns = _clip(s, t0_ns, t1_ns)
        if ns <= 0:
            continue
        bin_name, width_fn = rule
        width = width_fn(s.get("attrs") or {})
        if bin_name == "lend_transition":
            a0 = s["start_ns"] if t0_ns is None \
                else max(s["start_ns"], t0_ns)
            a1 = s["start_ns"] + s.get("dur_ns", 0)
            if t1_ns is not None:
                a1 = min(a1, t1_ns)
            nested = sum(_overlap_ns(a0, a1, r0, r1)
                         for r0, r1 in reshapes)
            ns = max(ns - nested, 0)
        bins[bin_name] += (ns / 1e9) * width
        counts[s["name"]] = counts.get(s["name"], 0) + 1
    return bins, counts


def collect(device_seconds, spans, t0_ns=None, t1_ns=None, slo=None,
            tolerance=0.05, provenance=None):
    """Build the versioned goodput artifact.

    ``device_seconds`` is the ``DeviceLedger.device_seconds()`` dict
    (``by_owner``/``total``/``world_size``/``elapsed_s``/
    ``conserved``) — the time ground truth. ``spans`` is a tracing
    snapshot (``tracing.spans_snapshot()``); ``slo`` an optional
    ``SLOTracker.to_doc()``. ``tolerance`` bounds the per-owner
    classified-vs-ledger slack (classification bills wall × width
    from spans, the ledger bills lease lifetimes — scheduling gaps
    make classified ≤ ledger the invariant, never ==).
    """
    bins, counts = classify_spans(spans, t0_ns, t1_ns)
    total = float(device_seconds["total"])
    classified = sum(bins.values())
    bins = dict(bins)
    bins["idle"] = max(total - classified, 0.0)
    productive = sum(bins[b] for b in PRODUCTIVE_BINS)
    tax = sum(bins[b] for b in TAX_BINS)

    by_owner = {}
    owners_within = True
    for owner, owned in OWNER_BINS.items():
        ledger_s = float(device_seconds["by_owner"].get(owner, 0.0))
        cls = sum(bins[b] for b in owned)
        within = cls <= ledger_s * (1.0 + tolerance) + 0.05
        owners_within = owners_within and within
        by_owner[owner] = {"ledger_s": ledger_s,
                           "classified_s": cls, "within": within}

    world = int(device_seconds["world_size"])
    elapsed = float(device_seconds["elapsed_s"])
    expect = world * elapsed
    owner_sum = sum(float(v)
                    for v in device_seconds["by_owner"].values())
    ledger_conserved = expect > 0 and \
        abs(owner_sum - expect) <= 0.02 * expect
    doc = {
        "tool": "goodput",
        "kind": GOODPUT_KIND,
        "version": GOODPUT_VERSION,
        "created": time.time(),
        "window": {"elapsed_s": elapsed, "world_size": world,
                   "t0_ns": t0_ns, "t1_ns": t1_ns},
        "bins": bins,
        "goodput": {
            "productive_s": productive,
            "tax_s": tax,
            "idle_s": bins["idle"],
            "total_s": total,
            "fraction": (productive / total) if total > 0 else 0.0,
        },
        "by_owner": by_owner,
        "device_seconds": device_seconds,
        "conservation": {
            "tolerance": tolerance,
            "owner_sum_s": owner_sum,
            "expected_s": expect,
            "ledger_conserved": ledger_conserved,
            "owners_within": owners_within,
            "conserved": bool(ledger_conserved and owners_within
                              and device_seconds.get("conserved")),
        },
        "spans": {"counted": sum(counts.values()),
                  "by_name": counts},
    }
    if slo is not None:
        doc["slo"] = slo
    if provenance is not None:
        doc["provenance"] = provenance
    return doc


def summary(doc, max_bytes=2048):
    """Bounded, provenance-marked embed for bench artifacts (the
    serving/health summary pattern): bins + fraction + conservation
    verdict, guaranteed under ``max_bytes`` serialized."""
    if not isinstance(doc, dict) or doc.get("kind") != GOODPUT_KIND:
        return None
    g = doc.get("goodput", {})
    out = {
        "kind": "goodput_summary",
        "source": "profiling.goodput",
        "fraction": g.get("fraction"),
        "productive_s": g.get("productive_s"),
        "tax_s": g.get("tax_s"),
        "idle_s": g.get("idle_s"),
        "total_s": g.get("total_s"),
        "world_size": doc.get("window", {}).get("world_size"),
        "conserved": doc.get("conservation", {}).get("conserved"),
        "bins": {k: round(float(v), 4)
                 for k, v in sorted(doc.get("bins", {}).items())},
        "spans_counted": doc.get("spans", {}).get("counted"),
    }
    slo = doc.get("slo")
    if isinstance(slo, dict):
        out["slo_burn"] = {
            o["name"]: o.get("burn")
            for o in slo.get("objectives", [])[:8]}
    # hard bound: drop detail until it fits (provenance keys survive)
    for victim in ("slo_burn", "bins", "spans_counted"):
        if len(json.dumps(out)) <= max_bytes:
            break
        out.pop(victim, None)
    return out


def dump(path, doc):
    """Write the artifact atomically (tmp + rename)."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(json.dumps(doc, indent=1, sort_keys=True))
    os.replace(tmp, path)
    return doc
