"""Minimal xplane (``*.xplane.pb``) reader — measured per-op time.

``jax.profiler.start_trace`` writes TensorBoard's XSpace protobuf. The
schema is stable and small (XSpace > XPlane > XLine > XEvent with
interned event-metadata names), so rather than depending on tensorflow
for the generated bindings this decodes the protobuf wire format
directly with the stdlib: ~80 lines, no imports, runs anywhere the
repo is checked out.

Observed layouts this reader handles:

- XSpace.planes = field 1
- XPlane: id=1, name=2, lines=3, event_metadata map=4 (key=1,
  value=2 -> XEventMetadata{id=1, name=2})
- XLine: id=1, name=2, timestamp_ns=3, events=4, display_name=11
- XEvent: metadata_id=1, offset_ps=2, duration_ps=3

On the CPU backend the per-HLO-thunk events land on ``/host:CPU``
lines (``tf_XLAEigen/...``); on TPU they land on ``/device:TPU:N``
"XLA Ops" lines. Either way the event *names are HLO instruction
names* (modulo a ``.clone``/``.remat`` suffix from thunk splitting),
which is exactly the cost ledger's join key — see
:func:`measure_ops`.
"""
from __future__ import annotations

import glob
import os


def _varint(buf, i):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf):
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError("unsupported protobuf wire type %d" % wt)
        yield fn, wt, v


def _parse_event_metadata(buf):
    key = None
    name = None
    for fn, wt, v in _fields(buf):
        if fn == 1 and wt == 0:
            key = v
        elif fn == 2 and wt == 2:
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0 and key is None:
                    key = v2
                elif f2 == 2 and w2 == 2:
                    name = v2.decode("utf-8", "replace")
    return key, name


def _parse_line(buf):
    line = {"name": None, "timestamp_ns": 0, "events": []}
    for fn, wt, v in _fields(buf):
        if fn == 2 and wt == 2 and line["name"] is None:
            line["name"] = v.decode("utf-8", "replace")
        elif fn == 11 and wt == 2:
            line["name"] = v.decode("utf-8", "replace")
        elif fn == 3 and wt == 0:
            line["timestamp_ns"] = v
        elif fn == 4 and wt == 2:
            mid = None
            off_ps = 0
            dur_ps = 0
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    mid = v2
                elif f2 == 2 and w2 == 0:
                    off_ps = v2
                elif f2 == 3 and w2 == 0:
                    dur_ps = v2
            line["events"].append((mid, off_ps, dur_ps))
    return line


def parse_xspace(data):
    """bytes -> [{"name", "event_metadata": {id: name},
    "lines": [{"name", "timestamp_ns", "events": [(mid, off_ps,
    dur_ps)]}]}]."""
    planes = []
    for fn, wt, v in _fields(data):
        if fn != 1 or wt != 2:
            continue
        plane = {"name": None, "event_metadata": {}, "lines": []}
        for f2, w2, v2 in _fields(v):
            if f2 == 2 and w2 == 2:
                plane["name"] = v2.decode("utf-8", "replace")
            elif f2 == 4 and w2 == 2:
                key, name = _parse_event_metadata(v2)
                if key is not None:
                    plane["event_metadata"][key] = name
            elif f2 == 3 and w2 == 2:
                plane["lines"].append(_parse_line(v2))
        planes.append(plane)
    return planes


def find_xplane_files(profile_dir):
    """The ``*.xplane.pb`` artifacts under a ``jax.profiler`` capture
    directory (``<dir>/plugins/profile/<run>/<host>.xplane.pb``),
    newest run first."""
    pats = (os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.xplane.pb"),
            os.path.join(profile_dir, "*.xplane.pb"))
    found = []
    for p in pats:
        found.extend(glob.glob(p))
    return sorted(found, key=lambda p: os.path.getmtime(p),
                  reverse=True)


def load_xspace(profile_dir_or_file):
    path = profile_dir_or_file
    if os.path.isdir(path):
        files = find_xplane_files(path)
        if not files:
            raise FileNotFoundError(
                "no .xplane.pb under %s (did the capture succeed?)"
                % path)
        path = files[0]
    with open(path, "rb") as f:
        return parse_xspace(f.read())


def normalize_event_name(name):
    """Thunk-split suffixes back to the HLO instruction name."""
    if not name:
        return name
    for suffix in (".clone", ".remat", ".remat2"):
        while name.endswith(suffix):
            name = name[:-len(suffix)]
    return name


def _union_ps(intervals):
    total = 0
    cur_s = cur_e = None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def _is_device_line(plane_name, line_name):
    """Lines carrying XLA execution: on TPU the ``/device:TPU:N``
    planes ("XLA Ops"/"Steps"); on CPU the ``tf_XLA*`` thunk-executor
    lines of ``/host:CPU`` (both the per-thunk Eigen lines and the
    client line whose 'wait for completion' event covers the
    pool-offloaded work that carries no per-op name)."""
    if (plane_name or "").startswith("/device:"):
        return True
    return (line_name or "").startswith("tf_XLA")


def measure_ops(planes, instr_names):
    """Join captured events against HLO instruction names.

    Per-op attribution uses SELF time: a ``call.N`` thunk event wraps
    the fused computation's own event on the same line, and a
    ``while`` body re-emits its inner thunks every trip — nested
    matched intervals are subtracted from their enclosing event so one
    nanosecond of device time lands on exactly one row.

    The reconciliation quantity is ``window_s``: the interval union of
    every event on device/executor lines (timestamp-rebased so lines
    share one axis). On TPU those events are all named per-op; on the
    CPU backend Eigen offloads convolutions to pool threads that emit
    no per-op traceme, so ``window_s > covered_s`` and the difference
    is reported as unattributed executor time rather than silently
    dropped.

    Returns ``{"ops": {instr_name: {"count", "total_s", "self_s"}},
    "covered_s", "window_s", "matched_events"}`` (times in seconds,
    per capture — divide by step count for per-step numbers).
    """
    names = set(instr_names)
    ops = {}
    covered = []
    window = []
    matched_events = 0
    for plane in planes:
        metas = plane["event_metadata"]
        for line in plane["lines"]:
            base_ps = line["timestamp_ns"] * 1000
            device_line = _is_device_line(plane["name"], line["name"])
            evs = []
            for mid, off_ps, dur_ps in line["events"]:
                s, e = base_ps + off_ps, base_ps + off_ps + dur_ps
                if device_line:
                    window.append((s, e))
                name = normalize_event_name(metas.get(mid))
                if name in names:
                    evs.append((s, e, name))
            if not evs:
                continue
            matched_events += len(evs)
            covered.extend((s, e) for s, e, _ in evs)
            evs.sort(key=lambda t: (t[0], -t[1]))
            # nesting sweep: [start, end, name, child_ps]
            stack = []

            def close(frame):
                s, e, name, child = frame
                rec = ops.setdefault(
                    name, {"count": 0, "total_s": 0.0, "self_s": 0.0})
                rec["count"] += 1
                rec["total_s"] += (e - s) / 1e12
                rec["self_s"] += max(e - s - child, 0) / 1e12
                if stack:
                    stack[-1][3] += e - s

            for s, e, name in evs:
                while stack and s >= stack[-1][1]:
                    close(stack.pop())
                stack.append([s, e, name, 0])
            while stack:
                close(stack.pop())
    return {
        "ops": ops,
        "covered_s": _union_ps(covered) / 1e12,
        "window_s": _union_ps(window) / 1e12,
        "matched_events": matched_events,
    }
