"""HBM memory attribution: static liveness ledger, live-array census,
and the OOM postmortem.

PRs 4-6 finished the *time* axis of observability; this module is the
*memory* axis. The reference framework answers "where do the bytes go"
statically, with NNVM's PlanMemory pass over the graph (ref:
src/nnvm/plan_memory.cc — liveness intervals + an inplace pass); the
TPU-native counterpart has XLA do the planning, so the same question
is answered in three cooperating layers:

1. **Static liveness ledger** (stdlib-only, chip-free): reuse the PR 6
   HLO text parser to compute def-use buffer intervals over the entry
   computation of a compiled executable, sweep them into peak live
   bytes + the instruction executing at the peak, and rank the buffers
   live at that point — each keyed back to a framework op through the
   same named-scope / ``jit(<fn>)`` / fusion-rule attribution channels
   the cost ledger uses. Cross-checked against XLA's own
   ``compiled.memory_analysis()`` (argument+output+temp), which is
   CPU/TPU-identical in shape, so the whole ledger is tier-1 testable.

2. **Runtime census**: classify ``jax.live_arrays()`` into parameter /
   gradient / optimizer_state / io_buffer / kv_cache / activation roles via
   NDArray-layer tagging (weakref side table — ``jax.Array`` objects
   are immutable, the tag lives next to them, never on them), reported
   **per device shard** via ``addressable_shards`` so a ZeRO-3 run
   shows 1/dp bytes per device where a replicated run shows the full
   footprint. Exported as ``mx_memory_*`` telemetry gauges and a
   Perfetto counter track in the merged chrome trace.

3. **OOM postmortem**: :func:`maybe_oom_postmortem` at the executor /
   trainer / sharded-step seams catches XLA ``RESOURCE_EXHAUSTED``
   and writes one atomic artifact combining the ranked peak-liveness
   table, the live-array census, per-device allocator stats and a
   PR 5 flight-recorder dump — the memory analogue of the hang
   flight recorder.

Env: ``MXTPU_MEMORY_CENSUS`` (0 disables tagging + the census
collector), ``MXTPU_OOM_DUMP_PATH`` (postmortem destination).
CLI: ``tools/memory_report.py`` (table / --diff / --capture / --hlo).
"""
from __future__ import annotations

import json
import os
import sys
import time
import weakref

from . import hlo
from . import ledger as _ledger

MEMORY_LEDGER_VERSION = 1
CENSUS_VERSION = 1
POSTMORTEM_VERSION = 1

# the role taxonomy (docs/observability.md "Memory accounting").
# "activation" is the default for any live array nothing tagged —
# intermediates, eval results, user temporaries. "kv_cache" is the
# serving decode plane's paged block pool (serving/generate/kvcache.py
# tags both pool arrays and re-tags them after every donated step), so
# the census, per-device gauges and the OOM postmortem name the cache
# that dominates generative-serving HBM by its actual bytes.
ROLES = ("parameter", "gradient", "optimizer_state", "io_buffer",
         "kv_cache", "activation")

# ---------------------------------------------------------------------------
# static liveness ledger
# ---------------------------------------------------------------------------

# opcodes that alias/forward their operand buffers instead of defining
# storage of their own (XLA buffer assignment gives them no allocation)
_FORWARDING = {"tuple", "get-tuple-element", "bitcast",
               "bitcast-convert", "opt-barrier", "after-all"}

import re as _re

_ALIAS_PAIR_RE = _re.compile(r"\{\s*(\d*)\s*\}\s*:\s*\(\s*(\d+)\s*[,)]")


def parse_input_output_aliases(hlo_text):
    """{output tuple index: parameter number} donation pairs from the
    HloModule header, e.g.
    ``input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, {}) }``.
    The value is brace-nested, so the span is found by depth scan, not
    regex. Nested output indices ({0,1}) are rare at the entry and
    skipped."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return {}
    i = hlo_text.index("{", start)
    depth = 0
    for j in range(i, min(len(hlo_text), i + 100000)):
        c = hlo_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                body = hlo_text[i + 1:j]
                break
    else:
        return {}
    out = {}
    for oidx, pnum in _ALIAS_PAIR_RE.findall(body):
        out[int(oidx) if oidx else 0] = int(pnum)
    return out


def buffer_intervals(mod, aliases=None):
    """Def-use liveness intervals over the entry computation.

    Returns ``{buffer name: {"def": i, "last_use": j, "bytes": b,
    "instr": Instr, "aliased": bool}}`` where indices are positions in
    the entry instruction list. Forwarding opcodes (tuple / gte /
    bitcast / opt-barrier) resolve through to the defining buffer —
    they own no storage. A fusion's internal producer/consumer buffers
    never appear at all: only entry instructions allocate (the fused
    temporaries live in registers/scratch, which is exactly the HBM
    accounting the cost ledger's bytes column already uses). Donated
    parameters (``aliases``: output index -> parameter number) keep
    the donor parameter live through the aliased output's definition
    and zero the output's own footprint — the output writes into the
    donor's buffer (the reference's inplace pass, compiler-decided).
    """
    instrs = mod.entry_instructions
    index = {ins.name: i for i, ins in enumerate(instrs)}
    resolve_cache = {}

    def resolve(name):
        """Underlying storage-owning buffer names for ``name``."""
        got = resolve_cache.get(name)
        if got is not None:
            return got
        i = index.get(name)
        if i is None:
            out = ()
        else:
            ins = instrs[i]
            if ins.opcode in _FORWARDING:
                resolve_cache[name] = ()   # cycle guard
                out = []
                for op in ins.operands:
                    out.extend(resolve(op))
                out = tuple(dict.fromkeys(out))
            else:
                out = (name,)
        resolve_cache[name] = out
        return out

    bufs = {}
    end = len(instrs) - 1
    for i, ins in enumerate(instrs):
        if ins.opcode in _FORWARDING:
            continue
        # non-donated argument buffers are owned by the caller and
        # stay resident for the WHOLE execution — live [0, end]
        # regardless of where the parameter instruction sits in the
        # text. They can never be reused for temporaries, which is why
        # memory_analysis() sums argument bytes wholesale. Donation is
        # the exception; the aliased-output bookkeeping below accounts
        # for it.
        first, last = (0, end) if ins.opcode == "parameter" else (i, i)
        bufs[ins.name] = {"def": first, "last_use": last,
                          "bytes": hlo.shape_bytes(ins.shape),
                          "instr": ins, "aliased": False}
    for i, ins in enumerate(instrs):
        for op in ins.operands:
            for name in resolve(op):
                b = bufs.get(name)
                if b is not None and i > b["last_use"]:
                    b["last_use"] = i
    # outputs stay live to the end of the program. is_output marks
    # reachability from the root — a temp merely CONSUMED by the last
    # instruction shares its last_use index but is not an output
    root = next((ins for ins in instrs if ins.is_root), None)
    root_bufs = resolve(root.name) if root is not None else ()
    for name in root_bufs:
        if name in bufs:
            bufs[name]["last_use"] = end
            bufs[name]["is_output"] = True
    # donated params: the aliased output reuses the donor's storage
    if aliases and root is not None:
        # output tuple component k = root's k-th operand when the root
        # is a forwarding tuple, else the root itself for index 0;
        # parameter numbers follow textual order in XLA dumps
        comps = (root.operands if root.opcode == "tuple"
                 else [root.name])
        pnum_order = [ins.name for ins in instrs
                      if ins.opcode == "parameter"]
        for oidx, pnum in aliases.items():
            if oidx >= len(comps) or pnum >= len(pnum_order):
                continue
            donor = pnum_order[pnum]
            for name in resolve(comps[oidx]):
                b = bufs.get(name)
                if b is None or name == donor:
                    continue
                b["aliased"] = True
                b["bytes"] = 0
                d = bufs.get(donor)
                if d is not None and b["def"] > d["last_use"]:
                    d["last_use"] = b["def"]
    return bufs


def _sweep_peak(bufs, n):
    """(peak_bytes, peak_index) from interval deltas."""
    if n <= 0:
        return 0, 0
    delta = [0] * (n + 1)
    for b in bufs.values():
        delta[b["def"]] += b["bytes"]
        delta[b["last_use"] + 1] -= b["bytes"]
    live = peak = 0
    peak_i = 0
    for i in range(n):
        live += delta[i]
        if live > peak:
            peak = live
            peak_i = i
    return peak, peak_i


def build_memory_ledger(hlo_text, fn_map=None, rule_map=None,
                        module=None, top=None):
    """Price an optimized-HLO module into a memory-ledger document:
    peak live bytes, the instruction at the peak, and the ranked table
    of buffers live at that point, attributed to framework ops (and,
    for fused clusters, the subgraph rule that made them — the same
    channels as the cost ledger)."""
    mod = module if module is not None else hlo.parse_module(hlo_text)
    if fn_map is None:
        fn_map = _ledger.framework_fn_map()
    if rule_map is None:
        rule_map = _ledger.fusion_rule_map()
    aliases = parse_input_output_aliases(hlo_text) if hlo_text else {}
    bufs = buffer_intervals(mod, aliases=aliases)
    instrs = mod.entry_instructions
    n = len(instrs)
    peak, peak_i = _sweep_peak(bufs, n)
    rows = []
    arg_bytes = const_bytes = 0
    out_bytes = 0
    for name, b in bufs.items():
        ins = b["instr"]
        if ins.opcode == "parameter":
            arg_bytes += b["bytes"]
        elif ins.opcode == "constant":
            const_bytes += b["bytes"]
        is_out = b.get("is_output") and not b["aliased"] \
            and ins.opcode != "parameter"
        if is_out:
            out_bytes += b["bytes"]
        if not (b["def"] <= peak_i <= b["last_use"]) or b["bytes"] == 0:
            continue
        kind = ("argument" if ins.opcode == "parameter" else
                "constant" if ins.opcode == "constant" else
                "output" if is_out else "temp")
        op = _ledger.attribute_op_name(ins.op_name, fn_map)
        row = {
            "buffer": name,
            "hlo_op": ins.opcode,
            "op": op,
            "bytes": b["bytes"],
            "kind": kind,
            "born": b["def"],
            "dies": b["last_use"],
        }
        rule = rule_map.get(op)
        if rule:
            row["rule"] = rule
        rows.append(row)
    rows.sort(key=lambda r: (-r["bytes"], r["buffer"]))
    # aggregates are computed over the FULL live-at-peak set; `top`
    # bounds only the stored per-buffer table
    by_op = group_buffers_by_op(rows)
    live_at_peak = len(rows)
    if top is not None:
        rows = rows[:top]
    return {
        "version": MEMORY_LEDGER_VERSION,
        "kind": "memory_ledger",
        "module": mod.name,
        "peak_live_bytes": peak,
        "peak_index": peak_i,
        "peak_instr": instrs[peak_i].name if 0 <= peak_i < n else None,
        "totals": {
            "instructions": n,
            "buffers": len(bufs),
            "live_at_peak": live_at_peak,
            "arg_bytes": arg_bytes,
            "constant_bytes": const_bytes,
            "output_bytes": out_bytes,
        },
        "buffers": rows,
        "by_op": by_op,
    }


def group_buffers_by_op(rows):
    """Live-at-peak bytes re-aggregated on the framework-op
    attribution (the ranked answer to "which op's buffers hold the
    HBM at the worst moment")."""
    agg = {}
    for r in rows:
        key = r.get("op") or r["hlo_op"]
        a = agg.setdefault(key, {"op": key, "buffers": 0, "bytes": 0})
        a["buffers"] += 1
        a["bytes"] += r["bytes"]
        if r.get("rule"):
            a["rule"] = r["rule"]
        kinds = a.setdefault("kinds", {})
        kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
    return sorted(agg.values(), key=lambda a: -a["bytes"])


def from_compiled(compiled, hlo_text=None, **kwargs):
    """Memory ledger from a ``jax.stages.Compiled``, cross-checked
    against XLA's own ``memory_analysis()`` buffer-assignment totals
    (argument + output + temp = what the arena must hold at peak).
    Pass ``hlo_text``/``module=`` to share one serialization/parse
    with a cost-ledger pass over the same executable."""
    if hlo_text is None:
        hlo_text = compiled.as_text()
    doc = build_memory_ledger(hlo_text, **kwargs)
    try:
        ma = compiled.memory_analysis()
        xla = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(
                ma.generated_code_size_in_bytes),
        }
        # aliased output bytes live in the donor argument's buffer;
        # XLA reports them in BOTH argument and alias columns, so the
        # resident total counts them once
        xla["total_bytes"] = (xla["argument_bytes"]
                              + xla["output_bytes"]
                              + xla["temp_bytes"]
                              - xla["alias_bytes"])
        doc["xla_memory_analysis"] = xla
        if xla["total_bytes"] > 0:
            doc["peak_vs_xla"] = round(
                doc["peak_live_bytes"] / xla["total_bytes"], 4)
    except Exception:  # noqa: BLE001 — memory_analysis is best-effort
        pass
    return doc


def from_fn(fn, *args, **kwargs):
    """Lower+compile ``fn`` on the current backend and build its
    memory ledger (plain callables are jitted here)."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return from_compiled(jitted.lower(*args).compile(), **kwargs)


def summarize(doc, top=5):
    """Bounded summary for embedding in bench artifacts."""
    out = {
        "peak_live_mb": round(doc["peak_live_bytes"] / 1e6, 3),
        "peak_instr": doc.get("peak_instr"),
        "top": [{"op": g["op"], "mb": round(g["bytes"] / 1e6, 3)}
                for g in doc.get("by_op", [])[:top]],
    }
    if "peak_vs_xla" in doc:
        out["peak_vs_xla"] = doc["peak_vs_xla"]
    return out


def diff(before, after):
    """Ranked per-op delta of live-at-peak bytes between two memory
    ledgers — the ``memory_report --diff`` payload, mirroring
    ``telemetry_dump --diff`` / ``mfu_report --diff``."""
    def index(doc):
        return {g["op"]: g for g in doc.get("by_op", [])}

    ia, ib = index(before), index(after)
    out = []
    for op in sorted(set(ia) | set(ib)):
        a, b = ia.get(op, {}), ib.get(op, {})
        out.append({
            "op": op,
            "before_bytes": a.get("bytes", 0),
            "after_bytes": b.get("bytes", 0),
            "delta_bytes": b.get("bytes", 0) - a.get("bytes", 0),
        })
    out.sort(key=lambda r: -abs(r["delta_bytes"]))
    return {
        "peak_before": before.get("peak_live_bytes", 0),
        "peak_after": after.get("peak_live_bytes", 0),
        "peak_delta": (after.get("peak_live_bytes", 0)
                       - before.get("peak_live_bytes", 0)),
        "by_op": out,
    }


def load(path):
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "peak_live_bytes" not in doc:
        raise ValueError("%s is not a memory-ledger document" % path)
    return doc


def dump(doc, path):
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc


# ---------------------------------------------------------------------------
# runtime census
# ---------------------------------------------------------------------------

_census = [os.environ.get("MXTPU_MEMORY_CENSUS", "1") not in (
    "0", "off", "false")]


def census_enabled():
    """MXTPU_MEMORY_CENSUS gate (default on) for tagging + collector.
    Cached at import — the tag seams run per parameter per step, so
    the gate must be one list read, not an environ lookup."""
    return _census[0]


def set_census_enabled(on):
    _census[0] = bool(on)


# id(jax.Array) -> (weakref, role). A weakref (ArrayImpl supports it)
# with a delete callback keeps the table from pinning arrays or
# serving a recycled id; tag writes are one dict store — hot-path safe
_TAGS = {}


def tag_role(x, role):
    """Tag a device array (jax.Array, NDArray, or anything exposing
    ``._data``) with a census role. No-op for non-array leaves and
    when MXTPU_MEMORY_CENSUS=0."""
    if not census_enabled():
        return x
    data = getattr(x, "_data", x)
    try:
        key = id(data)
        ref = weakref.ref(data, lambda _r, _k=key: _TAGS.pop(_k, None))
    except TypeError:
        return x  # numpy scalar / tracer / non-weakref-able
    _TAGS[key] = (ref, str(role))
    return x


def tag_tree(tree, role):
    """Tag every array leaf of a pytree (params dict, optimizer state
    tuple, batch list). Safe without jax imported: falls back to a
    shallow walk over lists/tuples/dicts."""
    if not census_enabled():
        return tree
    if "jax" in sys.modules:
        import jax
        jax.tree_util.tree_map(lambda leaf: tag_role(leaf, role), tree)
        return tree
    if isinstance(tree, dict):
        for v in tree.values():
            tag_tree(v, role)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            tag_tree(v, role)
    else:
        tag_role(tree, role)
    return tree


def role_of(x):
    """The tagged census role of an array, or None."""
    data = getattr(x, "_data", x)
    got = _TAGS.get(id(data))
    if got is None:
        return None
    ref, role = got
    return role if ref() is data else None


def live_census(arrays=None, top=0):
    """Classify live device arrays into roles, per device shard.

    ``arrays`` defaults to ``jax.live_arrays()`` (the whole process);
    pass an explicit list/tree to census just those (the ZeRO tests
    do, to isolate from unrelated suite state). Shard bytes come from
    ``addressable_shards`` metadata — no device sync, no transfer. A
    replicated array contributes its full size on EVERY device; a
    1/dp-sharded array contributes 1/dp per device — which is exactly
    the per-device proof ROADMAP item 2 asks for."""
    doc = {"version": CENSUS_VERSION, "kind": "memory_census",
           "ts": time.time(), "arrays": 0, "total_bytes": 0,
           "by_role": {}, "by_device": {}}
    if arrays is None:
        if not census_enabled():
            # tagging was off, so a whole-process walk would classify
            # every parameter/gradient as "activation" — confidently
            # wrong role totals are worse than an empty, marked doc.
            # An EXPLICIT arrays= request is still honored.
            doc["disabled"] = True
            return doc
        if "jax" not in sys.modules:
            return doc
        import jax
        try:
            arrays = jax.live_arrays()
        except Exception:  # noqa: BLE001 — backend init can fail headless
            return doc
    else:
        if "jax" in sys.modules:
            import jax
            arrays = jax.tree_util.tree_leaves(arrays)
        arrays = [getattr(a, "_data", a) for a in arrays]
    tops = []
    for arr in arrays:
        role = role_of(arr) or "activation"
        try:
            shards = arr.addressable_shards
        except Exception:  # noqa: BLE001 — deleted/donated buffers
            continue
        total = 0
        for sh in shards:
            try:
                nb = int(sh.data.nbytes)
                dev = sh.device
            except Exception:  # noqa: BLE001 — shard without data
                continue
            dkey = "%s:%d" % (getattr(dev, "platform", "dev"),
                              getattr(dev, "id", 0))
            d = doc["by_device"].setdefault(
                dkey, {"total_bytes": 0, "by_role": {}})
            d["total_bytes"] += nb
            d["by_role"][role] = d["by_role"].get(role, 0) + nb
            total += nb
        r = doc["by_role"].setdefault(role, {"bytes": 0, "arrays": 0})
        r["bytes"] += total
        r["arrays"] += 1
        doc["arrays"] += 1
        doc["total_bytes"] += total
        if top:
            tops.append((total, {
                "shape": list(getattr(arr, "shape", ())),
                "dtype": str(getattr(arr, "dtype", "?")),
                "role": role, "bytes": total}))
    if top:
        tops.sort(key=lambda t: -t[0])
        doc["top"] = [t[1] for t in tops[:top]]
    return doc


# ---------------------------------------------------------------------------
# OOM postmortem
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Allocation failure", "failed to allocate")
# the short marker only as a standalone word: '/models/BLOOM-7b' in an
# unrelated error message must not read as an allocation failure
_OOM_WORD_RE = _re.compile(r"\bOOM\b")


def is_oom_error(e):
    """Whether an exception is an XLA/PJRT allocation failure. Checked
    on the message, not the type: the backend raises XlaRuntimeError,
    RuntimeError, or jaxlib-versioned subclasses depending on where
    allocation fails."""
    s = str(e)
    return any(m in s for m in _OOM_MARKERS) or \
        _OOM_WORD_RE.search(s) is not None


def oom_dump_path():
    return os.environ.get("MXTPU_OOM_DUMP_PATH") or "oom_postmortem.json"


def _device_stats():
    out = {}
    if "jax" not in sys.modules:
        return out
    import jax
    try:
        devs = jax.local_devices()
    except Exception:  # noqa: BLE001
        return out
    for d in devs:
        fn = getattr(d, "memory_stats", None)
        try:
            stats = fn() if fn is not None else None
        except Exception:  # noqa: BLE001 — per-device support varies
            stats = None
        if stats:
            out["%s:%d" % (d.platform, d.id)] = {
                k: stats[k] for k in ("bytes_in_use",
                                      "peak_bytes_in_use",
                                      "bytes_limit") if k in stats}
    return out


def oom_postmortem(error=None, hlo_text=None, compiled=None,
                   source=None, path=None, extra=None):
    """Write the combined memory artifact: ranked peak-liveness table
    (when the failing program's HLO is reachable), live-array census,
    per-device allocator stats, and a flight-recorder dump. Atomic
    write; every section is individually guarded — a postmortem must
    never raise over the OOM it documents."""
    doc = {"version": POSTMORTEM_VERSION, "kind": "oom_postmortem",
           "ts": time.time()}
    if source:
        doc["source"] = str(source)[:120]
    if error is not None:
        doc["error"] = str(error)[:800]
        doc["error_type"] = type(error).__name__
    if compiled is not None and hlo_text is None:
        try:
            hlo_text = compiled.as_text()
        except Exception:  # noqa: BLE001
            pass
    if callable(hlo_text):
        try:
            hlo_text = hlo_text()
        except Exception:  # noqa: BLE001 — re-lowering can itself fail
            hlo_text = None
    if hlo_text:
        try:
            led = build_memory_ledger(hlo_text)
            led["buffers"] = led["buffers"][:25]
            doc["memory_ledger"] = led
        except Exception as e:  # noqa: BLE001
            doc["memory_ledger_error"] = repr(e)[:200]
    try:
        doc["census"] = live_census(top=10)
    except Exception as e:  # noqa: BLE001
        doc["census_error"] = repr(e)[:200]
    doc["device_stats"] = _device_stats()
    try:
        from ..tracing import flight as _flight
        doc["flight"] = _flight.snapshot(max_spans=10)
    except Exception as e:  # noqa: BLE001
        doc["flight_error"] = repr(e)[:200]
    if extra:
        doc.update(extra)
    path = path or oom_dump_path()
    try:
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        doc["path"] = path
    except OSError as e:
        doc["write_error"] = repr(e)[:200]
        print("[mxtpu] OOM postmortem write failed: %r" % (e,),
              file=sys.stderr, flush=True)
    return doc


def maybe_oom_postmortem(error, source=None, hlo_text=None,
                         compiled=None):
    """Seam helper: write a postmortem iff ``error`` is an allocation
    failure; always returns None so callers just re-raise. One
    artifact per process per failure burst: repeated OOMs inside one
    second coalesce (retry loops must not grind the disk)."""
    if error is None or not is_oom_error(error):
        return None
    now = time.monotonic()
    if now - _LAST_POSTMORTEM[0] < 1.0:
        return None
    _LAST_POSTMORTEM[0] = now
    try:
        return oom_postmortem(error=error, source=source,
                              hlo_text=hlo_text, compiled=compiled)
    except Exception as e:  # noqa: BLE001 — never mask the real OOM
        print("[mxtpu] OOM postmortem failed: %r" % (e,),
              file=sys.stderr, flush=True)
        return None


_LAST_POSTMORTEM = [-10.0]
