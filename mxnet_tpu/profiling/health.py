"""Model-health plane: nonfinite sentry, gradient/update telemetry,
loss-anomaly detection, first-NaN postmortem, drift fingerprints.

PRs 4-7 built four observability layers that all answer *performance*
questions (how much / why / where / which bytes); this module is the
*numerics* axis — "is the model still training correctly?". The
reference's instrument here is ``Monitor`` (per-op output statistics
through the executor monitor callback, ref: python/mxnet/monitor.py +
graph_executor.cc:1294); the TPU-native counterpart must answer the
same question WITHOUT host syncs, so it is built on the metric.py
accumulate-on-device / drain-at-read pattern in four layers:

1. **Nonfinite sentry** (:func:`check`): the framework seams —
   executor forward/backward, gluon ``Trainer.step`` gradients, the
   optimizer ``Updater``, the sharded train step — hand their output
   trees in; the sentry dispatches ONE fused nonfinite-count reduce
   per seam (a lazy device scalar, never read here) into a bounded
   pending window. :func:`step_boundary` folds only entries older
   than the window — dispatched many steps ago, so ``float()`` is a
   ready-buffer read, not a pipeline stall. A nonzero fold *trips*
   the sentry: the first-NaN postmortem is written and the configured
   policy (warn / raise) applies.

2. **Training-health telemetry**: global grad norm, per-parameter-
   group weight/grad norms and update-to-weight ratios — computed as
   lazy device scalars in ``Trainer._update`` and handed to the
   ``mx_health_*`` gauge/histogram families via ``set_lazy`` /
   ``observe_lazy`` (telemetry folds them at snapshot time). Loss
   lands through :func:`observe_loss` and feeds an EWMA with z-score
   **spike** and flat-line **plateau** anomaly detection on the folded
   (host) values.

3. **First-NaN postmortem** (:func:`nan_postmortem`): the memory
   axis's OOM postmortem, for numerics. When the sentry trips at an
   executor seam, :func:`localize_first_nonfinite` replays the
   executor's per-op monitor pass (every internal tensor, one jitted
   program) and BINARY-SEARCHES the topo-ordered prefix for the first
   op whose output is nonfinite — the prefix predicate "any nonfinite
   in internals[:k]" is monotone, so log2(n) tiny device reads replace
   an n-tensor transfer. One atomic artifact lands at
   ``MXTPU_HEALTH_DUMP_PATH``: offending op + named-scope attribution
   (the ``mx.<Op>`` channel the cost/memory ledgers key on), its input
   stats, the ranked per-group grad-norm table, RNG state from the
   checkpoint layer's vocabulary, and a flight-recorder snapshot.

4. **Drift fingerprints** (:func:`fingerprint_params`): a blake2b
   digest over a deterministically-ordered pytree flatten — one
   vocabulary for the bit-identical-resume tests, the chaos suite's
   bounded-drift assertions, and cross-backend ``consistency.py``
   rows. This is a *read-time* API (it materializes every leaf);
   never call it per hot-path step.

Env: ``MXTPU_HEALTH`` (0 = every hook a no-op; 1/warn = default;
raise = trip raises :class:`NonfiniteError`), ``MXTPU_HEALTH_DUMP_PATH``
(postmortem destination), ``MXTPU_HEALTH_NORMS`` (0 disables the norm
telemetry; the sentry stays), ``MXTPU_HEALTH_ANOMALY_Z`` (loss-spike
z-score threshold). CLI: ``tools/health_report.py`` (table / --diff /
--postmortem). Docs: docs/observability.md "Model health".
"""
from __future__ import annotations

import contextlib
import functools
import hashlib
import json
import os
import sys
import threading
import time

HEALTH_DOC_VERSION = 1
NAN_POSTMORTEM_VERSION = 1

# completed steps a sentry scalar buffers before it folds: entries
# fold _FOLD_LAG boundaries after their dispatch, so int() is a
# ready-buffer read of a long-retired tiny reduce, never a stall on
# in-flight compute (metric.py's _PENDING_WINDOW rationale, counted
# in steps here because one step may hold many per-source checks)
_FOLD_LAG = 4


class NonfiniteError(ArithmeticError):
    """Raised at a step boundary under MXTPU_HEALTH=raise when the
    sentry folded a nonzero nonfinite count. Carries the postmortem
    document (``.postmortem``) when one was written."""

    def __init__(self, msg, postmortem=None):
        super().__init__(msg)
        self.postmortem = postmortem


# -- gates ------------------------------------------------------------------
def _parse_policy(raw):
    raw = (raw or "1").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return None
    if raw in ("raise", "fatal"):
        return "raise"
    return "warn"


_policy = [_parse_policy(os.environ.get("MXTPU_HEALTH"))]
_norms = [os.environ.get("MXTPU_HEALTH_NORMS", "1") not in (
    "0", "off", "false")]


def enabled():
    """MXTPU_HEALTH gate (default on). Cached at import — the seams
    run per step, so the gate must be one list read, not an environ
    lookup."""
    return _policy[0] is not None


def policy():
    """'warn' | 'raise' | None (disabled)."""
    return _policy[0]


def set_enabled(on):
    """Flip the health plane at runtime. ``on`` may be a bool or a
    policy string ('warn'/'raise'/'0')."""
    if isinstance(on, str):
        _policy[0] = _parse_policy(on)
    else:
        _policy[0] = "warn" if on else None


def norms_enabled():
    """MXTPU_HEALTH_NORMS gate for the per-group norm telemetry."""
    return enabled() and _norms[0]


def set_norms_enabled(on):
    _norms[0] = bool(on)


def anomaly_z():
    try:
        return float(os.environ.get("MXTPU_HEALTH_ANOMALY_Z", "6"))
    except ValueError:
        return 6.0


def dump_path():
    return os.environ.get("MXTPU_HEALTH_DUMP_PATH") or \
        "nan_postmortem.json"


# -- telemetry families -----------------------------------------------------
def _lazy_met():
    from ..telemetry import metrics as _tm
    return _tm, _tm.lazy_metrics(lambda reg: {
        "nonfinite": reg.counter(
            "mx_health_nonfinite_total",
            "nonfinite (NaN/Inf) values folded by the sentry, by "
            "framework seam", labelnames=("source",)),
        "trips": reg.counter(
            "mx_health_trips_total",
            "sentry trips (first nonzero fold per burst)").labels(),
        "loss": reg.gauge(
            "mx_health_loss", "last folded training loss").labels(),
        "loss_ewma": reg.gauge(
            "mx_health_loss_ewma",
            "EWMA of the folded training loss").labels(),
        "anomalies": reg.counter(
            "mx_health_loss_anomalies_total",
            "loss anomalies detected (z-score spike / flat-line "
            "plateau)", labelnames=("kind",)),
        "grad_norm": reg.gauge(
            "mx_health_grad_norm",
            "global gradient L2 norm (lazy; folded at snapshot)"
            ).labels(),
        "group_weight": reg.gauge(
            "mx_health_weight_norm",
            "per-parameter-group weight L2 norm",
            labelnames=("group",)),
        "group_grad": reg.gauge(
            "mx_health_grad_norm_group",
            "per-parameter-group gradient L2 norm",
            labelnames=("group",)),
        "group_ratio": reg.gauge(
            "mx_health_update_ratio",
            "per-parameter-group update-to-weight norm ratio "
            "||dw||/||w||", labelnames=("group",)),
        "ratio_hist": reg.histogram(
            "mx_health_update_to_weight",
            "distribution of per-group update-to-weight ratios "
            "(dimensionless)",
            buckets=(1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0)),
    })


_met_box = []


def _met():
    """(telemetry.metrics module, built metric bundle) — memoized."""
    if not _met_box:
        _met_box.append(_lazy_met())
    tm, lazy = _met_box[0]
    return tm, lazy()


# -- sentry state -----------------------------------------------------------
class _HealthState:
    def __init__(self):
        self.lock = threading.Lock()
        self.step = 0                    # boundaries observed
        self.open = {}                   # {source: lazy count} this step
        # ONE localizer slot per source, overwritten every check: the
        # closure pins its step's inputs (weights + batch) for replay,
        # so holding one per banked entry would keep ~_FOLD_LAG
        # superseded copies of the model alive. A lagged trip replays
        # the NEWEST payload instead — nonfinite state persists across
        # steps, so the first-op attribution stands, and the pinned
        # memory is bounded to one step per seam.
        self.latest_loc = {}             # {source: localizer}
        self.pending = []                # [(step, {src: lazy count})]
        self.loss_pending = []           # [(step, scalar)]
        self.nonfinite_total = 0
        self.raised_total = 0            # nonfinites already raised for
        self.by_source = {}
        self.first_trip = None
        self.last_postmortem = -10.0     # monotonic; burst coalescing
        # loss EWMA / anomaly detection (folded host values only)
        self.loss_last = None
        self.loss_ewma = None
        self.loss_var = 0.0
        self.loss_n = 0
        self.anomalies = []              # bounded record of events
        self.plateau_run = 0
        self.plateau_fired = False
        # last folded norm table {group: {...}} + global grad norm
        self.norm_groups = {}
        self.grad_norm = None
        self.norm_pending = []           # [(step, lazy outs)] un-folded
        self.last_doc = None             # most recent postmortem doc


_state = _HealthState()

# loss EWMA decay + anomaly warmup/plateau knobs (docs/observability.md
# "Model health" documents the semantics; the z threshold is the env)
_EWMA_ALPHA = 0.05
_ANOMALY_WARMUP = 20
_PLATEAU_EPS = 1e-5
_PLATEAU_STEPS = 25


def _nonfinite_count(tree):
    """One fused lazy device scalar: total nonfinite values across the
    float leaves of ``tree``. Dispatch only — never read here."""
    import jax
    import jax.numpy as jnp

    total = None
    for leaf in jax.tree_util.tree_leaves(tree):
        data = getattr(leaf, "_data", leaf)
        dt = getattr(data, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.inexact):
            continue
        c = jnp.sum(~jnp.isfinite(data))
        total = c if total is None else total + c
    return total


def _accumulate(source, scalar, localize=None):
    """Bank a lazy nonfinite-count scalar into this step's per-source
    bucket (a lazy add — one scalar per source per step on device)."""
    source = str(source)
    st = _state
    with st.lock:
        prev = st.open.get(source)
        st.open[source] = scalar if prev is None else prev + scalar
        if localize is not None:
            st.latest_loc[source] = localize


def check(source, tree, localize=None):
    """Sentry seam: count nonfinites in ``tree`` as a lazy device
    scalar and accumulate it into this step's per-source bucket.
    ``localize`` is an optional zero-arg callable returning a
    first-op localization dict (the executor seams pass a closure
    over :func:`localize_first_nonfinite`); it is invoked only if the
    bucket folds nonzero. No-op when MXTPU_HEALTH=0."""
    if not enabled():
        return
    total = _nonfinite_count(tree)
    if total is None:
        return
    _accumulate(source, total, localize)


def check_scalar(source, value, localize=None):
    """Sentry seam for a single scalar (a sharded step's loss)."""
    check(source, [value], localize=localize)


def observe_loss(value):
    """Buffer a (possibly lazy) per-step training loss; folded
    _FOLD_LAG boundaries later into the EWMA + anomaly detector.
    No-op when disabled."""
    if not enabled():
        return
    data = getattr(value, "_data", value)
    st = _state
    with st.lock:
        st.loss_pending.append((st.step, data))


def _fold_entries(entries, boundary=None):
    """Fold ready sentry step-buckets to host; nonzero counts trip.
    ``boundary`` names the boundary doing the folding (trainer /
    module_fit / sharded_train_step / flush) — recorded on the trip
    so triage knows which loop surfaced it."""
    for step, by_source in entries:
        for source, scalar in by_source.items():
            try:
                n = int(scalar)
            except (TypeError, ValueError, OverflowError):
                continue
            if n <= 0:
                continue
            with _state.lock:
                localize = _state.latest_loc.get(source)
            _trip(step, source, n, localize, boundary=boundary)


def _trip(step, source, count, localize, boundary=None):
    st = _state
    tm, met = _met()
    with st.lock:
        st.nonfinite_total += count
        st.by_source[source] = st.by_source.get(source, 0) + count
        first = st.first_trip is None
        if first:
            st.first_trip = {"step": step, "source": source,
                             "count": count, "ts": time.time(),
                             "folded_by": boundary}
    if tm.enabled():
        met["nonfinite"].labels(source=source).inc(count)
    doc = None
    now = time.monotonic()
    with st.lock:
        burst = now - st.last_postmortem < 1.0
        if not burst:
            st.last_postmortem = now
    if tm.enabled() and not burst:
        # one trip event per burst (matches the postmortem coalescing,
        # so dashboards count bursts, not every poisoned step)
        met["trips"].inc()
    if not burst:
        # one artifact per failure burst (the OOM postmortem's
        # coalescing rule): a poisoned run trips every step
        doc = nan_postmortem(step=step, source=source, count=count,
                             localize=localize)
        with st.lock:
            st.last_doc = doc
    # the raise policy is enforced at step_boundary(), never here: a
    # window-overflow fold inside a seam's dispatch path must not turn
    # that seam into the raise site
    print("[mxtpu] health: nonfinite values detected: %d at seam %r "
          "(step %d)%s"
          % (count, source, step,
             " — postmortem at %s" % doc.get("path")
             if doc and doc.get("path") else ""),
          file=sys.stderr, flush=True)


def _fold_loss(step, value):
    try:
        x = float(value)
    except (TypeError, ValueError, OverflowError):
        return
    st = _state
    tm, met = _met()
    kind = None
    with st.lock:
        st.loss_last = x
        st.loss_n += 1
        if x != x or x in (float("inf"), float("-inf")):
            pass  # nonfinite loss: the sentry seam owns that signal
        elif st.loss_ewma is None:
            st.loss_ewma = x
        else:
            dev = x - st.loss_ewma
            std = st.loss_var ** 0.5
            if st.loss_n > _ANOMALY_WARMUP:
                if std > 0 and abs(dev) / std > anomaly_z():
                    kind = "spike"
                elif abs(dev) <= _PLATEAU_EPS * max(abs(st.loss_ewma),
                                                   1e-12):
                    st.plateau_run += 1
                    if st.plateau_run >= _PLATEAU_STEPS and \
                            not st.plateau_fired:
                        kind = "plateau"
                        st.plateau_fired = True
                else:
                    st.plateau_run = 0
                    st.plateau_fired = False
            st.loss_ewma += _EWMA_ALPHA * dev
            st.loss_var = ((1 - _EWMA_ALPHA) *
                           (st.loss_var + _EWMA_ALPHA * dev * dev))
        if kind is not None:
            st.anomalies.append({"step": step, "kind": kind,
                                 "loss": x, "ewma": st.loss_ewma})
            del st.anomalies[:-32]
        ewma = st.loss_ewma
    if tm.enabled():
        met["loss"].set(x)
        if ewma is not None:
            met["loss_ewma"].set(ewma)
        if kind is not None:
            met["anomalies"].labels(kind=kind).inc()


# -- per-group norm telemetry ----------------------------------------------
_GROUP_SUFFIXES = ("_weight", "_bias", "_gamma", "_beta", "_mean",
                   "_var")


def group_of(name):
    """Parameter-group key: the parameter name with its trailing
    role suffix stripped (dense0_weight and dense0_bias share the
    'dense0' group, matching how users reason about layers)."""
    for suf in _GROUP_SUFFIXES:
        if name.endswith(suf):
            return name[:-len(suf)] or name
    return name


@functools.lru_cache(maxsize=64)
def _probe_program(group_idx, want_norms):
    """One jitted program computing the WHOLE per-step probe: grad and
    weight nonfinite counts plus (``want_norms``) per-group weight/
    grad norms, global grad norm and update-to-weight ratios.
    ``group_idx`` is the parameter→group partition as INDICES (not
    names — two nets whose layers differ only in auto-generated name
    counters share one executable; jit itself re-specializes on leaf
    shapes/dtypes). After the first step this is ONE cached dispatch
    per step — XLA fuses the dozens of tiny reduces the eager version
    would dispatch one by one."""
    import jax
    import jax.numpy as jnp

    def fn(ws, gs, olds):
        gnf = pnf = jnp.int32(0)
        total_g2 = jnp.float32(0)
        acc = {}
        for gi, w, g, old in zip(group_idx, ws, gs, olds):
            w32 = w.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            gnf = gnf + jnp.sum(~jnp.isfinite(g32))
            pnf = pnf + jnp.sum(~jnp.isfinite(w32))
            if want_norms:
                w2 = jnp.sum(w32 * w32)
                g2 = jnp.sum(g32 * g32)
                u32 = w32 - old.astype(jnp.float32)
                u2 = jnp.sum(u32 * u32)
                total_g2 = total_g2 + g2
                a = acc.setdefault(gi, [jnp.float32(0)] * 3)
                a[0] = a[0] + w2
                a[1] = a[1] + g2
                a[2] = a[2] + u2
        out = {"grad_nf": gnf, "param_nf": pnf}
        if want_norms:
            out["grad_norm"] = jnp.sqrt(total_g2)
            out["groups"] = {
                gi: {"weight_norm": jnp.sqrt(a[0]),
                     "grad_norm": jnp.sqrt(a[1]),
                     "update_ratio": jnp.sqrt(a[2]) / jnp.maximum(
                         jnp.sqrt(a[0]), 1e-12)}
                for gi, a in acc.items()}
        return out

    return jax.jit(fn)


class StepProbe:
    """Per-step probe over a trainer's (weight, grad, pre-update
    weight) triples. ``add`` is a python list append; ``commit``
    runs the cached jitted probe program — one dispatch — banks the
    nonfinite counts into the ``trainer_grad``/``trainer_param``
    sentry buckets, hands the lazy norms to the gauges, and queues
    them for the lagged host fold at the boundary."""

    __slots__ = ("_names", "_ws", "_gs", "_olds", "step", "_norms")

    def __init__(self, step, want_norms):
        self.step = step
        self._norms = want_norms
        self._names = []
        self._ws = []
        self._gs = []
        self._olds = []

    @property
    def wants_norms(self):
        """Whether the caller should hand pre-update weights to
        ``add`` — with MXTPU_HEALTH_NORMS=0 capturing them would pin
        a full superseded model copy the program never reads."""
        return self._norms

    def add(self, name, weight, grad, weight_before=None):
        self._names.append(str(name))
        self._ws.append(getattr(weight, "_data", weight))
        self._gs.append(getattr(grad, "_data", grad))
        self._olds.append(getattr(weight_before, "_data",
                                  weight_before))

    def commit(self):
        if not self._ws:
            return
        want = self._norms and all(o is not None for o in self._olds)
        groups = []           # first-occurrence order
        group_idx = []
        for n in self._names:
            grp = group_of(n)
            if grp not in groups:
                groups.append(grp)
            group_idx.append(groups.index(grp))
        olds = self._olds if want else [w for w in self._ws]
        try:
            outs = _probe_program(tuple(group_idx), want)(
                self._ws, self._gs, olds)
        except Exception:  # noqa: BLE001 — an unjittable leaf (host
            # numpy of odd dtype) degrades to the plain sentry count
            check("trainer_grad", self._gs)
            check("trainer_param", self._ws)
            return
        _accumulate("trainer_grad", outs["grad_nf"])
        _accumulate("trainer_param", outs["param_nf"])
        if not want:
            return
        named = {groups[gi]: e for gi, e in outs["groups"].items()}
        outs = {"grad_nf": outs["grad_nf"],
                "param_nf": outs["param_nf"],
                "grad_norm": outs["grad_norm"], "groups": named}
        tm, met = _met()
        if tm.enabled():
            met["grad_norm"].set_lazy(outs["grad_norm"])
            for grp, e in named.items():
                met["group_weight"].labels(group=grp).set_lazy(
                    e["weight_norm"])
                met["group_grad"].labels(group=grp).set_lazy(
                    e["grad_norm"])
                met["group_ratio"].labels(group=grp).set_lazy(
                    e["update_ratio"])
                met["ratio_hist"].observe_lazy(e["update_ratio"])
        with _state.lock:
            _state.norm_pending.append((self.step, outs))
            del _state.norm_pending[:-8]


def step_probe(step=None):
    """A :class:`StepProbe` for this step, or None when the health
    plane is off entirely. With MXTPU_HEALTH_NORMS=0 the probe still
    runs the (cheaper) sentry-only program."""
    if not enabled():
        return None
    return StepProbe(_state.step if step is None else step,
                     norms_enabled())


# optimizer Updater calls inside a probe-covered trainer loop skip
# their own per-call check — the probe's one fused program already
# sees every (grad, weight) pair this step
_covered = threading.local()


def updater_is_covered():
    return getattr(_covered, "depth", 0) > 0


@contextlib.contextmanager
def updater_covered():
    _covered.depth = getattr(_covered, "depth", 0) + 1
    try:
        yield
    finally:
        _covered.depth -= 1


def _fold_norms(all_pending=True, horizon=None):
    """Fold queued lazy norm tables into host floats. At a boundary
    only tables >= _FOLD_LAG steps old fold (ready buffers); read
    paths (flush/postmortem) fold everything — syncs are the
    contract there."""
    st = _state
    with st.lock:
        if all_pending:
            ready, st.norm_pending = st.norm_pending, []
        else:
            ready = [e for e in st.norm_pending if e[0] < horizon]
            if ready:
                st.norm_pending = st.norm_pending[len(ready):]
    if not ready:
        return
    _step, outs = ready[-1]     # gauge semantics: newest wins
    if "groups" not in outs:
        return
    groups = {}
    for grp, entry in outs["groups"].items():
        row = {}
        for k, v in entry.items():
            try:
                row[k] = float(v)
            except (TypeError, ValueError, OverflowError):
                continue
        groups[grp] = row
    with st.lock:
        st.norm_groups = groups
        try:
            st.grad_norm = float(outs["grad_norm"])
        except (TypeError, ValueError, OverflowError, KeyError):
            pass


# -- boundaries / folding ---------------------------------------------------
def step_boundary(source="trainer", span=None):
    """Close one health step: bank this step's per-source buckets,
    fold every banked bucket ≥ _FOLD_LAG boundaries old (ready
    buffers — their reduces retired steps ago), and stamp lagged
    health attrs on the caller's step ``span`` so trace_merge can
    show which rank went unhealthy. Trips (and the raise policy)
    surface HERE, at the boundary, never inside a seam's dispatch
    path."""
    if not enabled():
        return None
    st = _state
    with st.lock:
        if st.open:
            st.pending.append((st.step, st.open))
            st.open = {}
        st.step += 1
        horizon = st.step - _FOLD_LAG
        ready = [e for e in st.pending if e[0] < horizon]
        if ready:
            st.pending = st.pending[len(ready):]
        loss_ready = [e for e in st.loss_pending if e[0] < horizon]
        if loss_ready:
            st.loss_pending = st.loss_pending[len(loss_ready):]
        # span attrs are LAGGED host state (previous folds) — reading
        # them costs nothing; the fresh entries fold below. Only
        # FINITE values land: span attrs flow verbatim into chrome
        # trace event args, where a bare NaN literal would make
        # Perfetto reject the whole document (the nonfinite signal
        # itself rides health_nonfinite)
        if span is not None:
            span.set_attr("health_nonfinite", st.nonfinite_total)
            for key, v in (("loss_ewma", st.loss_ewma),
                           ("grad_norm", st.grad_norm)):
                if v is not None and v == v and \
                        v not in (float("inf"), float("-inf")):
                    span.set_attr(key, round(v, 6))
    for step, v in loss_ready:
        _fold_loss(step, v)
    _fold_norms(all_pending=False, horizon=horizon)
    _fold_entries(ready, boundary=source)
    if policy() == "raise":
        with st.lock:
            fresh = st.nonfinite_total > st.raised_total
            st.raised_total = st.nonfinite_total
            trip, doc = st.first_trip, st.last_doc
        # raise only for NEWLY folded nonfinites: a caller that caught
        # the error, skipped the poisoned batch and kept training must
        # not be re-raised at every later (clean) boundary
        if fresh:
            raise NonfiniteError(
                "nonfinite values detected (total %d, first at seam "
                "%r step %s)" % (st.nonfinite_total,
                                 (trip or {}).get("source"),
                                 (trip or {}).get("step")),
                postmortem=doc)
    return None


def flush():
    """Force-fold EVERYTHING pending (a host sync): end-of-run
    verdicts, tests, artifact embedding. Returns :func:`snapshot_doc`.
    """
    st = _state
    with st.lock:
        if st.open:
            st.pending.append((st.step, st.open))
            st.open = {}
        ready, st.pending = st.pending, []
        loss_ready, st.loss_pending = st.loss_pending, []
    for step, v in loss_ready:
        _fold_loss(step, v)
    _fold_entries(ready, boundary="flush")
    _fold_norms()
    return snapshot_doc(fold=False)


def snapshot_doc(fold=True):
    """Point-in-time health summary document (the ``health`` embed in
    bench artifacts; health_report's table input)."""
    if fold:
        return flush()
    st = _state
    with st.lock:
        verdict = ("disabled" if not enabled() else
                   "nonfinite" if st.nonfinite_total else "clean")
        doc = {
            "version": HEALTH_DOC_VERSION,
            "kind": "health_summary",
            "enabled": enabled(),
            "policy": policy(),
            "steps": st.step,
            "sentry": {
                "verdict": verdict,
                "nonfinite_total": st.nonfinite_total,
                "by_source": dict(st.by_source),
                "first_trip": (dict(st.first_trip)
                               if st.first_trip else None),
            },
            "loss": {
                "last": st.loss_last,
                "ewma": st.loss_ewma,
                "std": (st.loss_var ** 0.5
                        if st.loss_ewma is not None else None),
                "observed": st.loss_n,
                "anomalies_total": len(st.anomalies),
                "anomalies": list(st.anomalies[-8:]),
            },
            "norms": {
                "grad_norm": st.grad_norm,
                "by_group": {g: dict(v)
                             for g, v in st.norm_groups.items()},
            },
        }
    return doc


def reset():
    """Drop all sentry/loss/norm state (test isolation; the telemetry
    families reset via the registry)."""
    global _state
    _state = _HealthState()


# -- first-NaN localization -------------------------------------------------
def localize_first_nonfinite(executor, arg_vals, aux_vals, key,
                             training=False):
    """Name the FIRST op (topo order) whose output holds a nonfinite.

    Replays the executor's per-op monitor pass once (the reference's
    ExecuteMonCallback internals program — every internal tensor, one
    jitted call, values stay on device), then binary-searches the
    prefix predicate "any nonfinite among internals[:k]". The
    predicate is monotone in k, and each probe reduces the candidate
    prefix on device to ONE bool — log2(n) 1-byte reads instead of
    transferring n tensors. Returns a dict naming the op through the
    named-scope attribution channel, with input/output stats, or None
    when every internal is finite (e.g. the nonfinite appeared only
    in backward)."""
    import jax.numpy as jnp
    import numpy as np

    names, vals = executor._monitor_internals(bool(training))(
        arg_vals, aux_vals, key)
    flags = []
    for v in vals:
        if jnp.issubdtype(v.dtype, jnp.inexact):
            flags.append(jnp.any(~jnp.isfinite(v)))
        else:
            flags.append(jnp.asarray(False))

    probes = [0]

    def prefix_bad(k):
        probes[0] += 1
        return bool(jnp.any(jnp.stack(flags[:k])))  # postmortem sync

    n = len(vals)
    if n == 0 or not prefix_bad(n):
        return None
    lo, hi = 1, n          # invariant: prefix_bad(hi) is True
    while lo < hi:
        mid = (lo + hi) // 2
        if prefix_bad(mid):
            hi = mid
        else:
            lo = mid + 1
    idx = lo - 1
    tensor_name = names[idx]
    node, out_k = executor._symbol.get_internals()._outputs[idx]

    def stats(arr):
        a = np.asarray(arr)
        out = {"shape": list(a.shape), "dtype": str(a.dtype)}
        if np.issubdtype(a.dtype, np.inexact) and a.size:
            finite = a[np.isfinite(a)]
            out["nonfinite"] = int(a.size - finite.size)
            if finite.size:
                out["min"] = float(finite.min())
                out["max"] = float(finite.max())
                out["mean"] = float(finite.mean())
        return out

    by_name = dict(zip(names, vals))
    inputs = []
    for child, k in node.inputs:
        suffix = "_output" if k == 0 else "_output%d" % k
        val = by_name.get(child.name + suffix)
        if val is None:   # graph input (variable / aux)
            val = arg_vals.get(child.name, aux_vals.get(child.name))
        entry = {"name": child.name}
        if val is not None:
            entry.update(stats(val))
        inputs.append(entry)
    return {
        "index": idx,
        "tensor": tensor_name,
        "node": node.name,
        "op": node.op,
        "named_scope": "mx.%s" % node.op if node.op else node.name,
        "attrs": {k: v for k, v in (node.attrs or {}).items()
                  if not k.startswith("__")},
        "probes": probes[0],
        "internals": n,
        "output": stats(vals[idx]),
        "inputs": inputs,
    }


# -- postmortem -------------------------------------------------------------
# zero-arg providers of extra postmortem context, run (guarded) at
# artifact time: Module.fit registers the data iterator's state_dict
# so the artifact pins the exact batch position, mirroring what
# CheckpointManager.save would have captured
_context_providers = {}


def register_postmortem_context(name, provider):
    """Register ``provider()`` to contribute a section to every future
    NaN postmortem under key ``name``; pass None to unregister."""
    if provider is None:
        _context_providers.pop(str(name), None)
    else:
        _context_providers[str(name)] = provider


def nan_postmortem(step=None, source=None, count=None, error=None,
                   localize=None, path=None, extra=None):
    """Write the first-NaN artifact: seam + first offending op (when a
    localizer is available), folded health state (loss EWMA +
    anomalies, ranked grad-norm table), RNG state from the checkpoint
    layer's vocabulary, and a flight-recorder snapshot. Atomic write;
    every section individually guarded — a postmortem must never raise
    over the numerics failure it documents."""
    doc = {"version": NAN_POSTMORTEM_VERSION, "kind": "nan_postmortem",
           "ts": time.time()}
    if source:
        doc["source"] = str(source)[:120]
    if step is not None:
        doc["step"] = int(step)
    # the artifact is written when the lagged fold TRIPS, up to
    # _FOLD_LAG boundaries after the failing step — the RNG/iterator
    # sections below are live state at capture time, offset by
    # (captured_at_step - step) from the failure (triage reads the
    # two fields together; the sync-free contract rules out capturing
    # them inside the hot step itself)
    doc["captured_at_step"] = _state.step
    doc["fold_lag"] = _FOLD_LAG
    if count is not None:
        doc["nonfinite_count"] = int(count)
    if error is not None:
        doc["error"] = str(error)[:800]
    if callable(localize):
        try:
            doc["first_op"] = localize()
        except Exception as e:  # noqa: BLE001 — replay can itself NaN out
            doc["first_op_error"] = repr(e)[:200]
    try:
        _fold_norms()
        summary = snapshot_doc(fold=False)
        doc["loss"] = summary["loss"]
        norms = summary["norms"]
        ranked = sorted(
            ((g, v) for g, v in norms["by_group"].items()
             if "grad_norm" in v),
            key=lambda kv: -kv[1]["grad_norm"])
        doc["grad_norms"] = {
            "global": norms["grad_norm"],
            "ranked": [{"group": g, **v} for g, v in ranked[:25]],
        }
        doc["sentry"] = summary["sentry"]
    except Exception as e:  # noqa: BLE001
        doc["health_state_error"] = repr(e)[:200]
    try:
        # the checkpoint layer's vocabulary (CheckpointManager saves
        # exactly these two states): the framework key chain is tiny
        # and lands verbatim; numpy's 624-word Mersenne state is
        # summarized. Captured at ARTIFACT time — see captured_at_step
        from .. import random as random_mod
        import numpy as np
        mx_state = random_mod.get_state()
        np_state = np.random.get_state()
        doc["rng"] = {
            "mx_key": np.asarray(mx_state).ravel().tolist(),
            "numpy": {"algo": str(np_state[0]),
                      "pos": int(np_state[2])},
        }
    except Exception as e:  # noqa: BLE001
        doc["rng_error"] = repr(e)[:200]
    for name, provider in list(_context_providers.items()):
        try:
            doc[name] = provider()
        except Exception as e:  # noqa: BLE001 — context is best-effort
            doc[name + "_error"] = repr(e)[:200]
    if extra:
        doc.update(extra)
    try:
        from ..tracing import flight as _flight
        doc["flight"] = _flight.snapshot(max_spans=10)
    except Exception as e:  # noqa: BLE001
        doc["flight_error"] = repr(e)[:200]
    path = path or dump_path()
    try:
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w", encoding="utf-8") as f:
            # allow_nan=False via pre-sanitization: NaN grad norms ARE
            # this artifact's payload, but bare NaN literals would make
            # the file unreadable to strict parsers (jq, other
            # languages) — they land as "nan"/"inf" strings
            json.dump(_json_sanitize(doc), f)
        os.replace(tmp, path)
        doc["path"] = path
    except OSError as e:
        doc["write_error"] = repr(e)[:200]
        print("[mxtpu] NaN postmortem write failed: %r" % (e,),
              file=sys.stderr, flush=True)
    return doc


def _json_sanitize(v):
    """Nonfinite floats -> their repr ("nan"/"inf" strings), so the
    artifact stays RFC-valid JSON for strict parsers. (Sibling guards:
    telemetry/export._json_safe for the chrome merge, tracing/
    export's local _finite for the standalone counter track.)"""
    if isinstance(v, float) and (
            v != v or v in (float("inf"), float("-inf"))):
        return repr(v)
    if isinstance(v, dict):
        return {k: _json_sanitize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_sanitize(x) for x in v]
    return v


# -- drift fingerprints -----------------------------------------------------
def iter_named_leaves(tree):
    """Deterministically-ordered ``[(path, leaf), ...]`` flatten of a
    dict/list/tuple pytree (None leaves skipped, paths "/"-joined and
    sorted lexicographically). THE one canonical walk: fingerprints
    hash it and the elastic checkpoint/reshard substrate keys its
    payload with it, so a checkpoint's keys and a fingerprint's paths
    agree by construction — two implementations could drift apart and
    silently break the bit-identical-resume contract."""
    leaves = []

    def walk(node, path):
        if isinstance(node, dict):
            for k in sorted(node, key=str):
                walk(node[k], path + (str(k),))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, path + (str(i),))
        elif node is None:
            return
        else:
            leaves.append(("/".join(path), node))

    walk(tree, ())
    leaves.sort(key=lambda kv: kv[0])
    return leaves


def fingerprint_params(tree, digest_size=16):
    """blake2b digest over a deterministically-ordered flatten of a
    parameter pytree (dict/list/tuple of NDArray / jax / numpy
    leaves). Leaf paths sort lexicographically, and each leaf
    contributes path + shape + dtype + raw bytes, so two trees
    fingerprint equal iff they hold bit-identical values under the
    same names — the shared vocabulary for bit-identical-resume,
    chaos bounded-drift, and cross-backend consistency rows.
    Materializes every leaf to host: a checkpoint/verify-time API,
    never a per-step one."""
    import numpy as np

    leaves = iter_named_leaves(tree)
    h = hashlib.blake2b(digest_size=int(digest_size))
    for path, leaf in leaves:
        data = getattr(leaf, "_data", leaf)
        a = np.ascontiguousarray(np.asarray(data))
        h.update(path.encode("utf-8"))
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()
