"""Deployment-only inference API
(ref: include/mxnet/c_predict_api.h — 12 MXPred* functions the
reference's amalgamation builds for mobile/embedded; here the analogue
is a minimal class over a checkpoint that forwards with zero training
machinery and an optionally AOT-compiled executable).

    pred = mx.predictor.Predictor.from_checkpoint("model", 3,
                                                  {"data": (1, 3, 224, 224)})
    out = pred.forward(data=batch)          # numpy in, numpy out
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError


class Predictor:
    """MXPredCreate/SetInput/Forward/GetOutput rolled into one object."""

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 dev_type=None, dev_id=0):
        import jax

        from .ndarray.ndarray import NDArray

        # MXPredCreate's dev_type/dev_id select the device; None = the
        # backend default (the TPU under axon)
        self._device = None
        if dev_type is not None:
            matching = []
            for backend in (dev_type, "axon" if dev_type == "tpu" else None):
                if backend is None:
                    continue
                try:
                    matching = jax.devices(backend)
                    break
                except RuntimeError:
                    continue
            if not matching or dev_id >= len(matching):
                raise MXNetError(
                    f"Predictor: no device {dev_type}:{dev_id}; available "
                    f"platforms: {sorted({d.platform for d in jax.devices()})}")
            self._device = matching[dev_id]
        self._symbol = symbol
        self._input_names = list(input_shapes)
        self._shapes = dict(input_shapes)
        known = set(symbol.list_inputs())
        missing = [n for n in self._input_names if n not in known]
        if missing:
            raise MXNetError(f"Predictor: inputs {missing} not in graph")
        self._bindings = {}
        for k, v in list(arg_params.items()) + list(aux_params.items()):
            self._bindings[k] = v if isinstance(v, NDArray) else NDArray(v)
        self._jitted = None

    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, **kwargs):
        """Load `prefix-symbol.json` + `prefix-{epoch}.params`
        (MXPredCreate's file contract, c_predict_api.h)."""
        from .model import load_checkpoint

        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls(symbol, arg_params, aux_params, input_shapes, **kwargs)

    def _build(self):
        import jax

        from .ndarray.ndarray import NDArray

        names = sorted(self._bindings)
        vals = tuple(self._bindings[n]._data for n in names)

        def fwd(param_vals, inputs):
            b = {n: NDArray(v) for n, v in zip(names, param_vals)}
            for k, v in inputs.items():
                b[k] = NDArray(v)
            out = self._symbol.eval_dict(b)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o._data for o in outs)

        self._jitted = jax.jit(fwd)
        # committed params pin the computation to the selected device
        self._param_vals = jax.device_put(vals, self._device) \
            if self._device is not None else jax.device_put(vals)

    def forward(self, **inputs):
        """Run one forward; numpy (or NDArray) in, list of numpy out
        (MXPredSetInput + MXPredForward + MXPredGetOutput)."""
        import jax.numpy as jnp

        from .ndarray.ndarray import NDArray

        if self._jitted is None:
            self._build()
        feed = {}
        for k, v in inputs.items():
            if k not in self._shapes:
                raise MXNetError(f"Predictor: unknown input {k!r}")
            # preserve the caller's dtype (int token indices etc.), as
            # MXPredSetInput does
            arr = v._data if isinstance(v, NDArray) \
                else jnp.asarray(np.asarray(v))
            if tuple(arr.shape) != tuple(self._shapes[k]):
                raise MXNetError(
                    f"Predictor: input {k} shape {tuple(arr.shape)} != "
                    f"declared {tuple(self._shapes[k])} (reshape with a "
                    "new Predictor, as MXPredReshape does)")
            feed[k] = arr
        outs = self._jitted(self._param_vals, feed)
        return [np.asarray(o) for o in outs]

    def reshape(self, new_input_shapes):
        """New shapes -> new compiled executable (MXPredReshape)."""
        self._shapes.update(new_input_shapes)
        self._jitted = None
        return self
