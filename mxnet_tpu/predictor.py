"""Deployment-only inference API
(ref: include/mxnet/c_predict_api.h — 12 MXPred* functions the
reference's amalgamation builds for mobile/embedded; here the analogue
is a minimal class over a checkpoint that forwards with zero training
machinery and an optionally AOT-compiled executable).

    pred = mx.predictor.Predictor.from_checkpoint("model", 3,
                                                  {"data": (1, 3, 224, 224)})
    out = pred.forward(data=batch)          # numpy in, numpy out
"""
from __future__ import annotations

import threading

import numpy as np

from .base import MXNetError


def compile_symbol_forward(symbol, bindings, device=None, cast=None):
    """The one symbol→executable lowering both deployment layers use
    (Predictor._build and the serving VariantSet — a fix here reaches
    both): commit ``bindings`` (params/aux, NDArray or array-like) to
    ``device`` as a sorted tuple and return ``(jitted, param_vals)``
    where ``jitted(param_vals, inputs_dict)`` evaluates the symbol and
    returns a tuple of jax arrays.

    ``cast`` (e.g. ``"bfloat16"``) builds a reduced-precision variant:
    float params are cast offline, float inputs at the graph edge, and
    float outputs cast back to fp32 (replies stay fp32-typed).
    """
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray

    names = sorted(bindings)
    cast_dt = jnp.dtype(cast) if cast is not None else None

    def _cast(a):
        if cast_dt is not None and jnp.issubdtype(a.dtype,
                                                  jnp.floating):
            return a.astype(cast_dt)
        return a

    vals = tuple(
        _cast(bindings[n]._data if isinstance(bindings[n], NDArray)
              else jnp.asarray(np.asarray(bindings[n])))
        for n in names)

    def fwd(param_vals, inputs):
        b = {n: NDArray(v) for n, v in zip(names, param_vals)}
        for k, v in inputs.items():
            b[k] = NDArray(_cast(jnp.asarray(v)))
        out = symbol.eval_dict(b)
        outs = out if isinstance(out, (list, tuple)) else [out]
        res = []
        for o in outs:
            a = o._data
            if cast_dt is not None and \
                    jnp.issubdtype(a.dtype, jnp.floating):
                a = a.astype(jnp.float32)
            res.append(a)
        return tuple(res)

    pvals = jax.device_put(vals, device) if device is not None \
        else jax.device_put(vals)
    return jax.jit(fwd), pvals


class Predictor:
    """MXPredCreate/SetInput/Forward/GetOutput rolled into one object."""

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 dev_type=None, dev_id=0, device=None):
        import jax

        from .ndarray.ndarray import NDArray

        # MXPredCreate's dev_type/dev_id select the device; None = the
        # backend default (the TPU under axon). ``device`` takes a jax
        # device object directly — the serving gateway pins one
        # replica's executables per device this way (serving/gateway.py)
        self._device = device
        if device is None and dev_type is not None:
            matching = []
            for backend in (dev_type, "axon" if dev_type == "tpu" else None):
                if backend is None:
                    continue
                try:
                    matching = jax.devices(backend)
                    break
                except RuntimeError:
                    continue
            if not matching or dev_id >= len(matching):
                raise MXNetError(
                    f"Predictor: no device {dev_type}:{dev_id}; available "
                    f"platforms: {sorted({d.platform for d in jax.devices()})}")
            self._device = matching[dev_id]
        self._symbol = symbol
        self._input_names = list(input_shapes)
        self._shapes = dict(input_shapes)
        known = set(symbol.list_inputs())
        missing = [n for n in self._input_names if n not in known]
        if missing:
            raise MXNetError(f"Predictor: inputs {missing} not in graph")
        self._bindings = {}
        for k, v in list(arg_params.items()) + list(aux_params.items()):
            self._bindings[k] = v if isinstance(v, NDArray) else NDArray(v)
        self._jitted = None
        # guards the lazy _build: the serving gateway's worker threads
        # race the first forward(); without this, two threads half-
        # initialize (_jitted set, _param_vals missing) and one crashes
        self._lock = threading.Lock()

    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, **kwargs):
        """Load `prefix-symbol.json` + `prefix-{epoch}.params`
        (MXPredCreate's file contract, c_predict_api.h)."""
        from .model import load_checkpoint

        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls(symbol, arg_params, aux_params, input_shapes, **kwargs)

    def _build(self):
        jitted, pvals = compile_symbol_forward(
            self._symbol, self._bindings, self._device)
        # committed params pin the computation to the selected device.
        # _param_vals is published BEFORE _jitted: forward()'s unlocked
        # fast path reads _jitted first, so it must never observe a
        # jitted fn without the params it closes over
        self._param_vals = pvals
        self._jitted = jitted

    def forward(self, **inputs):
        """Run one forward; numpy (or NDArray) in, list of numpy out
        (MXPredSetInput + MXPredForward + MXPredGetOutput)."""
        import jax.numpy as jnp

        from .ndarray.ndarray import NDArray

        jitted = self._jitted
        if jitted is None:
            with self._lock:          # double-checked: concurrent first
                if self._jitted is None:   # calls build exactly once
                    self._build()
                jitted = self._jitted
        # local snapshots: a concurrent reshape() nulls _jitted under
        # the lock — this call then runs the pre-reshape executable
        # (jit retraces per input shape, so even a racing new shape
        # computes correctly) instead of crashing on a None read
        pvals = self._param_vals
        feed = {}
        for k, v in inputs.items():
            if k not in self._shapes:
                raise MXNetError(f"Predictor: unknown input {k!r}")
            # preserve the caller's dtype (int token indices etc.), as
            # MXPredSetInput does
            arr = v._data if isinstance(v, NDArray) \
                else jnp.asarray(np.asarray(v))
            if tuple(arr.shape) != tuple(self._shapes[k]):
                raise MXNetError(
                    f"Predictor: input {k} shape {tuple(arr.shape)} != "
                    f"declared {tuple(self._shapes[k])} (reshape with a "
                    "new Predictor, as MXPredReshape does)")
            feed[k] = arr
        outs = jitted(pvals, feed)
        return [np.asarray(o) for o in outs]

    def reshape(self, new_input_shapes):
        """New shapes -> new compiled executable (MXPredReshape)."""
        with self._lock:
            self._shapes.update(new_input_shapes)
            self._jitted = None
        return self

    def output_shapes(self, dtypes=None):
        """Output shapes for the declared input shapes, WITHOUT running
        or compiling a forward (MXPredGetOutputShape is legal right
        after MXPredCreate in the reference ABI) — jax.eval_shape
        traces abstractly. Inputs default to float32 (the C ABI is
        float-only by signature); Python callers with integer inputs
        (token ids) pass ``dtypes={"data": "int32"}``."""
        import jax
        import jax.numpy as jnp

        from .ndarray.ndarray import NDArray

        dtypes = dtypes or {}
        bindings = {
            k: jax.ShapeDtypeStruct(
                tuple(v), jnp.dtype(dtypes.get(k, jnp.float32)))
            for k, v in self._shapes.items()}

        def absfwd(inputs):
            b = dict(self._bindings)
            for k, v in inputs.items():
                b[k] = NDArray(v)
            out = self._symbol.eval_dict(b)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o._data for o in outs)

        shaped = jax.eval_shape(absfwd, bindings)
        return [tuple(s.shape) for s in shaped]


class _CPredictor:
    """Bridge object behind the MXPred* C ABI (_native/predict.cc):
    one instance per PredictorHandle; the C side calls these methods
    under the GIL. Mirrors c_predict_api.h semantics: declared input
    shapes, set_input copies, forward compiles-and-runs, outputs are
    fetched as flat fp32."""

    # reference dev_type codes (c_predict_api.h: 1 cpu, 2 gpu) — the
    # accelerator code maps to this framework's chip backend
    _DEV = {1: "cpu", 2: "tpu"}

    def __init__(self, symbol_json, param_bytes, dev_type, dev_id,
                 input_names, input_shapes, output_names=()):
        from . import symbol as sym_mod
        from .ndarray.utils import load_frombuffer
        from .symbol.symbol import is_aux_name

        sym = sym_mod.load_json(symbol_json)
        if output_names:
            internals = sym.get_internals()
            names = internals.list_outputs()
            outs = []
            for name in output_names:
                cand = name if name in names else name + "_output"
                if cand not in names:
                    raise MXNetError(
                        f"MXPredCreatePartialOut: {name} not in graph")
                outs.append(internals[cand])
            sym = sym_mod.Group(outs)
        loaded = load_frombuffer(param_bytes)
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            if k.startswith("aux:"):
                aux_params[k[4:]] = v
            elif k.startswith("arg:"):
                arg_params[k[4:]] = v
            else:
                (aux_params if is_aux_name(k) else arg_params)[k] = v
        dev = self._DEV.get(int(dev_type)) if dev_type else None
        try:
            self._pred = Predictor(sym, arg_params, aux_params,
                                   dict(zip(input_names, input_shapes)),
                                   dev_type=dev, dev_id=int(dev_id))
        except MXNetError:
            if dev != "cpu":
                raise
            # cpu requested but jax only exposes the chip backend: the
            # default device is the deployment target anyway
            self._pred = Predictor(sym, arg_params, aux_params,
                                   dict(zip(input_names, input_shapes)))
        self._inputs = {}
        self._outputs = None
        self._abstract_shapes = None

    def set_input(self, key, flat):
        if key not in self._pred._shapes:
            raise MXNetError(f"MXPredSetInput: unknown input {key!r}")
        shape = tuple(self._pred._shapes[key])
        # copy: the C caller's buffer is only valid during the call
        arr = np.array(flat, np.float32, copy=True)
        if arr.size != int(np.prod(shape)):
            raise MXNetError(
                f"MXPredSetInput: {key} got {arr.size} elements, "
                f"shape {shape} needs {int(np.prod(shape))}")
        self._inputs[key] = arr.reshape(shape)
        self._outputs = None

    def forward(self):
        missing = [k for k in self._pred._shapes if k not in self._inputs]
        if missing:
            raise MXNetError(f"MXPredForward: inputs not set: {missing}")
        self._outputs = [np.asarray(o, np.float32)
                         for o in self._pred.forward(**self._inputs)]

    def reshaped(self, input_names, input_shapes):
        """A NEW bridge at the new shapes; this handle keeps serving its
        original shapes (reference MXPredReshape returns a fresh handle
        sharing weights, c_predict_api.h). Inputs not named keep their
        previous shapes, as the reference does."""
        unknown = [n for n in input_names
                   if n not in self._pred._shapes]
        if unknown:
            raise MXNetError(
                f"MXPredReshape: {unknown} are not inputs of this "
                f"predictor (declared: {sorted(self._pred._shapes)})")
        shapes = dict(self._pred._shapes)
        shapes.update(dict(zip(input_names, input_shapes)))
        clone = object.__new__(_CPredictor)
        p = Predictor.__new__(Predictor)
        p._device = self._pred._device
        p._symbol = self._pred._symbol
        p._input_names = list(shapes)
        p._shapes = shapes
        p._bindings = self._pred._bindings  # weights shared, not copied
        p._jitted = None
        p._lock = threading.Lock()
        clone._pred = p
        clone._inputs = {}
        clone._outputs = None
        clone._abstract_shapes = None
        return clone

    def _inferred_shapes(self):
        # one abstract trace per handle: shapes are fixed for its life
        if self._abstract_shapes is None:
            self._abstract_shapes = self._pred.output_shapes()
        return self._abstract_shapes

    def num_outputs(self):
        if self._outputs is None:
            return len(self._inferred_shapes())
        return len(self._outputs)

    def output_shape(self, index):
        if self._outputs is None:
            # legal straight after create: infer abstractly
            return self._inferred_shapes()[index]
        return tuple(self._outputs[index].shape)

    def output(self, index):
        self._ensure()
        return np.ascontiguousarray(self._outputs[index], np.float32)

    def _ensure(self):
        if self._outputs is None:
            raise MXNetError("MXPredGetOutput: call MXPredForward first")
