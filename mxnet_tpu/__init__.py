"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new implementation (not a port) of the capability surface of
pengzhao-intel/incubator-mxnet, designed for TPU: JAX/XLA is the compute and
scheduling substrate (the PJRT runtime replaces the threaded dependency
engine; XLA fusion replaces the MKL-DNN subgraph backend), Pallas provides
custom kernels, and jax.sharding/shard_map over device meshes replaces
KVStore's NCCL/ps-lite paths.

Usage mirrors the reference:

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

if (_os.environ.get("DMLC_ROLE") == "worker"
        and _os.environ.get("DMLC_NUM_SERVER") == "0"
        and _os.environ.get("DMLC_PS_ROOT_URI")
        and _os.environ.get("DMLC_PS_ROOT_PORT")
        and not _os.environ.get("_MXTPU_DIST_JOINED")):
    # serverless (collective) dist job from tools/launch.py -s 0: the
    # jax.distributed runtime must come up before ANY XLA backend touch,
    # so join the mesh at import — the analogue of ps-lite reading its
    # DMLC_* env at library init (ref: src/kvstore/kvstore_dist.h:44,
    # python/mxnet/kvstore_server.py import-time server entry)
    import jax as _jax

    _jax.distributed.initialize(
        coordinator_address="%s:%s" % (_os.environ["DMLC_PS_ROOT_URI"],
                                       _os.environ["DMLC_PS_ROOT_PORT"]),
        num_processes=int(_os.environ.get("DMLC_NUM_WORKER", "1")),
        process_id=int(_os.environ.get("DMLC_WORKER_ID", "0")))
    # children of this worker inherit the DMLC_* env; this marker stops
    # them from rejoining the mesh with a duplicate process_id
    _os.environ["_MXTPU_DIST_JOINED"] = "1"

from .base import MXNetError, get_env

# The lock witness must patch threading.* BEFORE any framework module
# constructs a lock, so this hook runs ahead of every subsystem import.
if get_env("MXTPU_LOCK_WITNESS", "0") not in ("0", "", "false", "off"):
    from .analysis import witness as _witness
    _witness.install()

from . import telemetry
from . import tracing
from . import profiling
from .context import (Context, cpu, cpu_pinned, current_context, gpu, num_gpus,
                      num_tpus, tpu)
from . import engine
from . import random
from . import ndarray
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import autograd

# Subsystem imports are appended as each lands (package layout matches the
# reference's python/mxnet/__init__.py).
from . import test_utils  # noqa: E402
from . import initializer  # noqa: E402
from . import initializer as init  # noqa: E402
from . import optimizer  # noqa: E402
from .optimizer import Optimizer  # noqa: E402
from . import lr_scheduler  # noqa: E402
from . import metric  # noqa: E402
from . import kvstore  # noqa: E402
from . import kvstore as kv  # noqa: E402
from . import recordio  # noqa: E402
from . import symbol  # noqa: E402
from . import symbol as sym  # noqa: E402
from . import attribute  # noqa: E402
from . import name  # noqa: E402
from . import registry  # noqa: E402
from . import log  # noqa: E402
from . import rtc  # noqa: E402
from . import executor_manager  # noqa: E402
from . import util  # noqa: E402
from . import misc  # noqa: E402
from . import kvstore_server  # noqa: E402
from . import libinfo  # noqa: E402
from .attribute import AttrScope  # noqa: E402
from .executor import Executor  # noqa: E402
from . import io  # noqa: E402
from . import callback  # noqa: E402
from . import model  # noqa: E402
from . import predictor  # noqa: E402
from . import serving  # noqa: E402
from . import elastic  # noqa: E402
from . import module  # noqa: E402
from . import module as mod  # noqa: E402
from . import rnn  # noqa: E402
from . import subgraph  # noqa: E402
from . import profiler  # noqa: E402
from . import checkpoint  # noqa: E402
from . import contrib  # noqa: E402
from . import gluon  # noqa: E402
from . import operator  # noqa: E402
from . import image  # noqa: E402
from . import monitor  # noqa: E402
from .monitor import Monitor  # noqa: E402
from . import visualization  # noqa: E402
from . import visualization as viz  # noqa: E402
