"""Model checkpointing helpers (ref: python/mxnet/model.py:383-450).

Format matches the reference: ``prefix-symbol.json`` (graph JSON) +
``prefix-####.params`` (NDArray map with ``arg:``/``aux:`` key
prefixes), so checkpoints are structurally diffable against MXNet's.
Both files are written through ``checkpoint.atomic_write`` (tmp +
fsync + rename, CRC32 in the sibling MANIFEST.json) via
``symbol.save``/``nd.save``, so a preemption mid-checkpoint leaves the
previous epoch's files intact and a corrupted ``.params`` is rejected
by CRC at ``load_checkpoint`` time instead of loading as wrong weights
(docs/robustness.md "Worker recovery & checkpoint format").
"""
from __future__ import annotations

import collections

from . import ndarray as nd
from . import symbol as sym_mod

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """(ref: model.py:383 save_checkpoint)"""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(ref: model.py:413 load_checkpoint)"""
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """The pre-Module estimator API (ref: python/mxnet/model.py:451
    FeedForward) — kept as a thin adapter over Module so legacy scripts
    (`FeedForward.create(...)`, `.fit/.predict/.score/.save/.load`)
    run unmodified. New code should use Module or Gluon.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self._kwargs = dict(kwargs)
        self._module = None

    def _init_iter(self, X, y, is_train):
        import numpy as np

        from .io import NDArrayIter
        if hasattr(X, "provide_data"):
            return X
        X = np.asarray(X)
        if y is None:
            y = np.zeros(X.shape[0], np.float32)
        return NDArrayIter(X, np.asarray(y),
                           batch_size=min(self.numpy_batch_size,
                                          X.shape[0]),
                           shuffle=is_train)

    def _optimizer_params(self):
        """The reference passes optimizer hyperparams as loose ctor
        kwargs (learning_rate=..., momentum=...); accept both that and
        an explicit optimizer_params dict (model.py:488 **kwargs)."""
        params = dict(self._kwargs.get("optimizer_params") or {})
        for k, v in self._kwargs.items():
            if k != "optimizer_params":
                params.setdefault(k, v)
        params.setdefault("learning_rate", 0.01)
        return params

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        """Train (ref: model.py:793 FeedForward.fit)."""
        from .module import Module

        data = self._init_iter(X, y, is_train=True)
        self._module = Module(self.symbol, context=self.ctx)
        self._module.fit(
            data, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer,
            optimizer_params=self._optimizer_params(),
            initializer=self.initializer,
            arg_params=self.arg_params, aux_params=self.aux_params,
            begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
            monitor=monitor, eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    def _ensure_module(self, data):
        """Lazy inference bind (load()ed models have no module yet)."""
        from .module import Module

        if self._module is None or not self._module.binded:
            self._module = Module(self.symbol, context=self.ctx)
            # loss-bearing graphs (SoftmaxOutput etc.) need the label
            # shape even at inference; _init_iter synthesizes one
            self._module.bind(data_shapes=data.provide_data,
                              label_shapes=data.provide_label,
                              for_training=False)
            self._module.set_params(self.arg_params or {},
                                    self.aux_params or {},
                                    allow_missing=False)
        return self._module

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Forward over X, concatenated to numpy
        (ref: model.py:673 predict)."""
        import numpy as np

        data = self._init_iter(X, None, is_train=False)
        mod = self._ensure_module(data)
        if not return_data:
            out = mod.predict(data, num_batch=num_batch, reset=reset)
            if isinstance(out, list):  # multi-output symbol / empty iter
                return [o.asnumpy() for o in out]
            return out.asnumpy()
        outs, datas, labels = [], [], []
        for outputs, _, batch in mod.iter_predict(data, num_batch=num_batch,
                                                  reset=reset):
            pad = batch.pad or 0
            end = batch.data[0].shape[0] - pad
            outs.append(outputs[0].asnumpy())
            datas.append(batch.data[0].asnumpy()[:end])
            labels.append(batch.label[0].asnumpy()[:end])
        return (np.concatenate(outs), np.concatenate(datas),
                np.concatenate(labels))

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        """Evaluate (ref: model.py:742 score)."""
        from . import metric as metric_mod

        data = self._init_iter(X, None, is_train=False)
        mod = self._ensure_module(data)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        mod.score(data, eval_metric, num_batch=num_batch,
                  batch_end_callback=batch_end_callback, reset=reset)
        return eval_metric.get()[1]

    def save(self, prefix, epoch=None):
        """Checkpoint in the reference's format (ref: model.py:895)."""
        save_checkpoint(prefix, epoch if epoch is not None
                        else (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """(ref: model.py:918 load)."""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", **kwargs):
        """Construct + fit in one call (ref: model.py:949 create)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            optimizer=optimizer, initializer=initializer,
                            **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore)
        return model
