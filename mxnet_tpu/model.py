"""Model checkpointing helpers (ref: python/mxnet/model.py:383-450).

Format matches the reference: ``prefix-symbol.json`` (graph JSON) +
``prefix-####.params`` (NDArray map with ``arg:``/``aux:`` key
prefixes), so checkpoints are structurally diffable against MXNet's.
"""
from __future__ import annotations

import collections

from . import ndarray as nd
from . import symbol as sym_mod

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """(ref: model.py:383 save_checkpoint)"""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(ref: model.py:413 load_checkpoint)"""
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
