"""Base utilities: errors, registries, env-var config.

TPU-native analogue of the reference's dmlc-core base layer
(ref: include/mxnet/base.h, python/mxnet/base.py). There is no C ABI
boundary here: the "engine" under this framework is the JAX/PJRT runtime
itself, so the base layer only carries errors, the op/class registries
and the env-var config tier (ref: docs/faq/env_var.md).
"""
from __future__ import annotations

import os
import threading


class MXNetError(RuntimeError):
    """Default error raised by the framework (ref: python/mxnet/base.py MXNetError)."""


class NotSupportedForSparseNDArray(MXNetError):
    pass


def get_env(name, default=None, dtype=str):
    """Read an env var the way the reference reads dmlc::GetEnv at point of use."""
    val = os.environ.get(name)
    if val is None:
        return default
    if dtype is bool:
        return val not in ("0", "false", "False", "")
    return dtype(val)


def attr_bool(val, default=False):
    """Normalize a graph-attr boolean that may arrive as bool, int, or a
    string spelling from externally produced symbol JSON ("True", "true",
    "1", "False", "false", "0") — plain truthiness would read "false" as
    True (the reference parses these through dmlc parameter boolean
    fields, which accept the same spellings)."""
    if val is None:
        return default
    if isinstance(val, str):
        s = val.strip().lower()
        if s in ("true", "1"):
            return True
        if s in ("false", "0", ""):
            return False
        raise MXNetError(f"cannot parse boolean attr {val!r}")
    return bool(val)


class _Registry:
    """Generic name -> object registry (ref: python/mxnet/registry.py)."""

    def __init__(self, kind):
        self.kind = kind
        self._entries = {}
        self._lock = threading.Lock()

    def register(self, obj, name=None, aliases=()):
        name = name or getattr(obj, "__name__", None)
        if name is None:
            raise ValueError("cannot infer registry name")
        with self._lock:
            self._entries[name.lower()] = obj
            for a in aliases:
                self._entries[a.lower()] = obj
        return obj

    def get(self, name):
        try:
            return self._entries[name.lower()]
        except KeyError:
            raise MXNetError(
                f"{self.kind} '{name}' is not registered. "
                f"Known: {sorted(set(self._entries))}"
            ) from None

    def find(self, name):
        return self._entries.get(name.lower())

    def entries(self):
        return dict(self._entries)


_registries = {}


def registry(kind):
    if kind not in _registries:
        _registries[kind] = _Registry(kind)
    return _registries[kind]


def classproperty(fn):
    class _cp:
        def __get__(self, obj, owner):
            return fn(owner)

    return _cp()


# Numeric limits used by quantization (ref: src/operator/quantization/quantization_utils.h)
INT8_MIN, INT8_MAX = -127, 127
INT32_MIN, INT32_MAX = -(2 ** 31) + 1, 2 ** 31 - 1
