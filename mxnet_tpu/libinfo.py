"""Runtime feature discovery + the canonical environment-variable list
(ref: python/mxnet/libinfo.py find_lib_path/__version__;
python/mxnet/runtime.py Features; docs/faq/env_var.md).

    >>> import mxnet_tpu as mx
    >>> mx.libinfo.features()          # what this build can do
    >>> mx.libinfo.env_vars()          # every honored env var + value
    >>> mx.libinfo.find_lib_path()     # built native libraries
"""
from __future__ import annotations

import os


def __getattr__(name):
    if name == "__version__":
        # single source of truth: the package (avoids two literals
        # drifting on a version bump)
        from mxnet_tpu import __version__ as v
        return v
    raise AttributeError(name)

# every environment variable the framework reads, with where it acts —
# the docs/faq/env_var.md analogue, kept next to the code so it cannot
# drift silently. (DMLC_* come from tools/launch.py's tracker contract.)
_ENV_VARS = {
    "MXNET_ENGINE_TYPE": (
        "ThreadedEnginePerDevice | NaiveEngine — NaiveEngine serializes "
        "every op (determinism/race-debug switch; engine.py)"),
    "MXNET_CPU_WORKER_NTHREADS": (
        "host worker threads for the native engine and decode pools "
        "(_native/core.cc, io pipeline)"),
    "MXNET_SUBGRAPH_BACKEND": (
        "graph-partition backend applied at bind, e.g. XLA "
        "(symbol.simple_bind; subgraph/xla_fuse.py)"),
    "MXNET_PROFILER_AUTOSTART": (
        "1 = profiling from import, chrome-trace on exit (profiler.py)"),
    "MXNET_HOME": (
        "root for local data: model store weights, text embeddings "
        "(default ~/.mxnet_tpu)"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (
        "elements above which dist pushes are sliced across servers "
        "(kvstore/dist.py)"),
    "MXNET_KVSTORE_REQUEST_TIMEOUT_MS": (
        "client-side dist request timeout; a dead server fails the job "
        "instead of hanging it (kvstore/dist.py)"),
    "MXNET_KVSTORE_RECOVERY_BUDGET_MS": (
        "total wall-clock a worker may spend recovering one failed dist "
        "request (reconnect + idempotent resend loop); 0 = legacy "
        "fail-fast (kvstore/dist.py, docs/robustness.md)"),
    "MXNET_KVSTORE_RECOVERY_BACKOFF_MS": (
        "initial reconnect backoff, doubled per attempt with ±25% "
        "jitter (default 50; kvstore/fault.py BackoffSchedule)"),
    "MXNET_KVSTORE_RECOVERY_BACKOFF_MAX_MS": (
        "backoff growth cap (default 2000; kvstore/fault.py)"),
    "MXNET_KVSTORE_RECOVERY_GRACE_MS": (
        "server-side: how long a missing worker may stay gone before "
        "the job degrades; defaults to the recovery budget "
        "(kvstore/dist.py run_server)"),
    "MXNET_KVSTORE_FAULT_PLAN": (
        "deterministic fault-injection plan, e.g. "
        "drop_conn@round=3;kill_server@round=5 "
        "(kvstore/fault.py, docs/robustness.md)"),
    "MXNET_KVSTORE_SNAPSHOT_PATH": (
        "server-side: SIGTERM snapshots the whole server state here and "
        "a restart restores it; set automatically by tools/launch.py "
        "--restart-policy=server (kvstore/dist.py run_server)"),
    "DMLC_ROLE": "worker|server — set per process by tools/launch.py",
    "DMLC_PS_ROOT_URI": "rendezvous host (launch.py tracker contract)",
    "DMLC_PS_ROOT_PORT": "rendezvous port; with -s 0 it is the "
                         "jax.distributed coordinator",
    "DMLC_NUM_WORKER": "worker count in the dist job",
    "DMLC_NUM_SERVER": "server count; 0 = collective data plane",
    "DMLC_WORKER_ID": "this worker's rank",
    "DMLC_SERVER_ID": "this server's index",
    "MXNET_TEST_SEED": (
        "pins unseeded framework RNG draws (weight init, dropout) for "
        "the whole process — the reference test harness's determinism "
        "contract (random.py)"),
    "MXTPU_NO_SERVER_AUTOINIT": (
        "1 = do NOT enter the server loop at import in a "
        "DMLC_ROLE=server process (the reference always enters; "
        "kvstore_server.py)"),
    "MXNET_CHECKPOINT_MANIFEST": (
        "0 disables the CRC32 MANIFEST.json that atomic checkpoint "
        "writes record and loads verify; worker resume still works but "
        "without CRC proof (default on; checkpoint.py, "
        "docs/robustness.md)"),
    "MXNET_WORKER_CHECKPOINT_DIR": (
        "per-worker directory for CheckpointManager training-state "
        "checkpoints; set automatically by tools/launch.py "
        "--restart-policy=worker so a respawned worker auto-resumes "
        "(checkpoint.py)"),
    "MXNET_WORKER_RESTARTS": (
        "how many times tools/launch.py has respawned this worker "
        "after preemption (set by the launcher; recorded in resume "
        "telemetry, checkpoint.py)"),
    "MXNET_GRAPH_VALIDATE": (
        "Symbol.validate() gate in simple_bind: warn (default) logs "
        "pre-bind graph findings, error raises, 0/off disables "
        "(symbol/symbol.py, analysis/graph.py)"),
    "MXTPU_IO_HOST_ENGINE": (
        "1 (default) schedules io pipeline decode/prefetch on the "
        "native host engine; 0 = plain thread fallback (io/io.py)"),
    "MXTPU_IO_WORKERS": (
        "decode worker PROCESSES for the sharded input pipeline; the "
        "default num_workers of ImageRecordIter and the "
        "ShardedRecordPipeline (0 = stay in-process; io/pipeline.py, "
        "docs/io.md)"),
    "MXTPU_IO_RING_BATCHES": (
        "batch slots per worker in the shared-memory ring (default 3; "
        "bounds decode run-ahead and host memory: "
        "workers x slots x batch bytes; io/pipeline.py)"),
    "MXTPU_IO_READAHEAD_MB": (
        "raw-byte readahead per streaming shard reader (default 64); "
        "background chunk reads overlap record parse + decode "
        "(recordio.RecordIOStreamReader, io/_pipeline_worker.py)"),
    "MXTPU_IO_PREFETCH_DEVICE": (
        "1 = double-buffered device prefetch by default: "
        "gluon DataLoader and Module.fit wrap their batch streams in "
        "the device feeder (jax.device_put of batch k+1 during step "
        "k); per-call prefetch_to_device= overrides (io/pipeline.py, "
        "docs/io.md)"),
    "MXTPU_COMPILE_CACHE": (
        "persistent XLA compile-cache directory so warm runs skip "
        "recompilation (tools/mfu_probe.py sets it per run)"),
    "MXTPU_PEAK_TFLOPS": (
        "per-chip peak TFLOPs used as the MFU denominator "
        "(tools/mfu_probe.py; default 197 = v5p bf16)"),
    "MXTPU_TELEMETRY": (
        "0 disables the metrics registry's hot-path instrumentation "
        "(op dispatch, io wait, kvstore bytes, step breakdown); "
        "default on (telemetry/, docs/observability.md)"),
    "MXTPU_TELEMETRY_FLUSH_SEC": (
        ">0 starts a daemon thread writing a JSON metric snapshot "
        "every N seconds to MXTPU_TELEMETRY_FILE (telemetry/__init__)"),
    "MXTPU_TELEMETRY_FILE": (
        "periodic-flush destination, atomically replaced each flush "
        "(default telemetry.json, or telemetry.<role><rank>.json "
        "inside a launch.py job so processes sharing a cwd don't "
        "overwrite each other; telemetry/__init__)"),
    "MXTPU_TELEMETRY_VERBOSE": (
        "1 logs a one-line summary to stderr at every telemetry flush "
        "(telemetry/__init__)"),
    "MXTPU_TRACE_SAMPLE": (
        "trace-level sampling probability for the span layer, 0..1 "
        "(default 1; 0 disables span recording entirely — the flight "
        "recorder then has nothing to dump; tracing/)"),
    "MXTPU_TRACE_RING": (
        "closed spans retained per thread ring (default 2048; "
        "tracing/)"),
    "MXTPU_TRACE_FILE": (
        "tracing.export.write_trace default path (default trace.json, "
        "or trace.<role><rank>.json inside a launch.py job; "
        "tracing/export.py)"),
    "MXTPU_HANG_TIMEOUT_SEC": (
        ">0 arms the hang watchdog at flight-recorder install: a step "
        "with no span activity for this long dumps in-flight spans + "
        "thread stacks (tracing/flight.py; bench.py arms it per run)"),
    "MXTPU_FLIGHT_PATH": (
        "flight-recorder dump destination (atomic file write; default "
        "stderr). bench.py points it at a per-run file it embeds in "
        "failure JSON (tracing/flight.py)"),
    "MXTPU_PROFILE_ATTRIB": (
        "0 disables the performance-attribution passes: bench.py's "
        "CPU cost-ledger subprocess and the post-capture xplane join "
        "(default on; profiling/, bench.py)"),
    "MXTPU_PROFILE_DIR": (
        "base directory for jax.profiler attribution captures "
        "(default <tmp>/mxtpu_profile; profiling/capture.py, "
        "tools/mfu_report.py --capture)"),
    "MXTPU_PEAK_HBM_GBS": (
        "per-chip HBM bandwidth in GB/s — the roofline's memory "
        "ceiling next to MXTPU_PEAK_TFLOPS (default 819 = v5e; "
        "profiling/ledger.py)"),
    "MXTPU_BENCH_BATCH": (
        "bench harness batch size; the cost-ledger pass compiles its "
        "stage programs at this batch (default 128; bench.py, "
        "profiling/bench_ledger.py)"),
    "MXTPU_LEDGER_OUT": (
        "cost-ledger pass output path; bench.py points it at a "
        "per-run file whose stage summaries every artifact embeds "
        "(profiling/bench_ledger.py)"),
    "MXTPU_LEDGER_STAGES": (
        "comma-separated bench_ledger stages to compile+price "
        "(default infer_bf16,train_bf16; 'tiny' is the seconds-fast "
        "test stage; profiling/bench_ledger.py)"),
    "MXTPU_LEDGER_DEADLINE_SEC": (
        "how long bench.py waits for the cost-ledger subprocess "
        "before killing it at final-artifact time (default 300; "
        "bench.py)"),
    "MXTPU_MEMORY_CENSUS": (
        "0 disables the live-array memory census: role tagging at the "
        "NDArray/optimizer/io seams and the mx_memory_* snapshot "
        "collector (default on; profiling/memory.py, "
        "docs/observability.md)"),
    "MXTPU_OOM_DUMP_PATH": (
        "OOM postmortem destination — an XLA RESOURCE_EXHAUSTED at "
        "the executor/trainer/sharded-step seams writes the ranked "
        "peak-liveness table + census + flight dump here (default "
        "oom_postmortem.json; bench.py points it at a per-run file "
        "it embeds in failure artifacts; profiling/memory.py)"),
    "MXTPU_SERVING_MAX_WAIT_MS": (
        "default continuous-batcher coalescing window per model: a "
        "request never waits longer than this for batch-mates before "
        "dispatching partial, so bs=1 latency is bounded (default 5; "
        "serving/batcher.py, docs/serving.md)"),
    "MXTPU_SERVING_MAX_QUEUE": (
        "default per-model queue-depth limit; submissions beyond it "
        "fast-reject with reason queue_full (default 256; "
        "serving/gateway.py)"),
    "MXTPU_SERVING_SLO_MS": (
        "default per-model latency budget: a request whose estimated "
        "e2e latency (EWMA service rate x backlog) would exceed it "
        "fast-rejects with reason slo; 0 disables (default 0; "
        "serving/gateway.py)"),
    "MXTPU_SERVING_REPLICAS": (
        "default replica count per registered model; degrades "
        "gracefully when fewer local devices exist (default 1; "
        "serving/gateway.py)"),
    "MXTPU_SERVING_HEALTH_SEC": (
        ">0 starts the gateway health-probe daemon at this period: "
        "failed replicas drain, recovered ones rejoin (default 0 = "
        "manual check_health(); serving/gateway.py)"),
    "MXTPU_SERVING_TP": (
        "default tensor-parallel width for registered models/"
        "generators: >= 2 makes every replica a MESH SLICE of that "
        "many devices serving one SPMD program per batch, parameters "
        "placed from the layout plane's role table (default 0 = "
        "single-device lanes; serving/sharded.py, parallel/layout.py, "
        "docs/serving.md)"),
    "MXTPU_LAYOUT_TABLE": (
        "path to a JSON layout-table override (SpecLayout.to_json "
        "format): SpecLayout.default() — the table serving slices, "
        "the sharded decode plane, and the dry-run CLI resolve "
        "through — loads it instead of the built-in role table "
        "(default unset; parallel/layout.py)"),
    "MXTPU_LAYOUT_REPORT": (
        "path: every sharded serving lane writes its per-parameter "
        "placement report (role/spec/per-device bytes, the "
        "layout_report document shape) here at registration, "
        "atomically (default unset; serving/sharded.py)"),
    "MXTPU_GEN_BLOCK_TOKENS": (
        "default KV-cache block size in tokens for registered "
        "generators — the paged-attention page granularity (default "
        "16; serving/generate/, docs/serving.md)"),
    "MXTPU_GEN_MAX_BLOCKS": (
        "default KV block-pool size per generator replica lane; "
        "block 0 is the reserved pad sink, and admission fast-rejects "
        "kv_cache_full when the pool cannot cover a request's token "
        "budget (default 256; serving/generate/kvcache.py)"),
    "MXTPU_GEN_MAX_NEW_TOKENS": (
        "default + cap for a generation request's max_new_tokens — "
        "bounds the block-table width the compiled decode step is "
        "traced with (default 64; serving/gateway.py "
        "register_generator)"),
    "MXTPU_GEN_MAX_RECOVERIES": (
        "decode failover budget: how many lane losses one in-flight "
        "generation survives (KV-block migration / deterministic "
        "replay) before degrading to a fast lane_lost reject "
        "(default 2; serving/generate/scheduler.py, "
        "docs/robustness.md)"),
    "MXTPU_GEN_RECOVERY_BACKOFF_MS": (
        "backoff base in ms between REPEAT recoveries of the same "
        "generation request, doubling per rescue and capped at 40x "
        "base — the first rescue is always immediate (default 50; "
        "serving/generate/scheduler.py)"),
    "MXTPU_FUSE_COST": (
        "0 disables cost-tracked partitioning at bind: "
        "MXNET_SUBGRAPH_BACKEND then applies the always-fire pattern "
        "pass instead of pricing each cluster with the flop/byte + "
        "liveness ledgers (default on when shapes are known; "
        "subgraph/cost.py, docs/architecture.md)"),
    "MXTPU_FUSE_MIN_SAVE": (
        "fractional roofline-time saving a candidate cluster must "
        "show to fuse (default 0.02 — a rewrite that buys <2% of the "
        "cluster's est_s stays unfused; subgraph/cost.py CostGate)"),
    "MXTPU_FUSE_MEM_SLACK_MB": (
        "absolute peak-live-bytes growth (MB) a fusing cluster may "
        "cost before the memory currency rejects it; the gate always "
        "tolerates 1% relative noise on top (default 0; "
        "subgraph/cost.py CostGate)"),
    "MXTPU_FUSE_REPORT": (
        "path: every cost-tracked partition pass writes its decision "
        "trail (the partition cost report, rendered by "
        "tools/mfu_report.py) here (default unset; subgraph/cost.py)"),
    "MXTPU_KERNEL_FUSED_OPT": (
        "route sgd_mom_update/adam_update through the fused Pallas "
        "one-pass update kernel: 1/0/auto (default auto = chip "
        "backends only; the jnp path is the CPU hot path and the "
        "kernel's numerics oracle; ops/optimizer_ops.py, "
        "ops/pallas_kernels.py)"),
    "MXTPU_HEALTH": (
        "model-health plane gate/policy: 0 = every hook a no-op, "
        "1/warn (default) = sentry + telemetry + postmortem then "
        "continue, raise = a nonfinite fold raises NonfiniteError at "
        "the step boundary (profiling/health.py, "
        "docs/observability.md)"),
    "MXTPU_HEALTH_DUMP_PATH": (
        "first-NaN postmortem destination — a sentry trip writes the "
        "offending-op localization + ranked grad norms + loss state "
        "+ RNG + flight dump here (default nan_postmortem.json; "
        "profiling/health.py)"),
    "MXTPU_HEALTH_NORMS": (
        "0 drops the norm half of the per-step probe program "
        "(per-group weight/grad norms + update-to-weight ratios and "
        "the pre-update weight capture); the nonfinite sentry stays "
        "on (default on; profiling/health.py, gluon/trainer.py)"),
    "MXTPU_HEALTH_ANOMALY_Z": (
        "z-score threshold for the loss-spike anomaly detector over "
        "the folded loss EWMA (default 6; profiling/health.py)"),
    "MXTPU_KERNEL_INT8_EPILOGUE": (
        "0 routes the fused INT8 conv epilogue (_sg_xla_quant_conv) "
        "through plain ops/quantized.py requantize+act instead of "
        "ops/pallas_kernels.quantized_conv_epilogue (default auto — "
        "the wrapper itself falls back off-chip; subgraph/rules.py)"),
    "MXTPU_ELASTIC_DIR": (
        "membership directory of an elastic job: workers announce "
        "join/leave as member-<rank>.json files here and the "
        "generation counter lives beside them (default unset = not "
        "an elastic job; elastic/membership.py, docs/robustness.md)"),
    "MXTPU_ELASTIC_POLL_SEC": (
        "serving autoscaler decision period when started as a daemon "
        "(default 2; elastic/autoscale.py)"),
    "MXTPU_ELASTIC_MIN_REPLICAS": (
        "autoscaler floor: scale-in never retires below this many "
        "serving lanes (default 1; elastic/autoscale.py)"),
    "MXTPU_ELASTIC_MAX_REPLICAS": (
        "autoscaler ceiling before the degraded-wrap cap: scale-out "
        "never builds past this many lanes (default 4; "
        "elastic/autoscale.py)"),
    "MXTPU_ELASTIC_QUEUE_HIGH": (
        "per-replica queue-depth EWMA high watermark — sustained "
        "pressure above queue_high x replicas scales out; the low "
        "watermark defaults to a quarter of it (default 8; "
        "elastic/autoscale.py)"),
    "MXTPU_ELASTIC_P99_BUDGET_MS": (
        "autoscaler latency budget: a windowed e2e p99 estimate "
        "(mx_serving_latency_seconds bucket deltas) above it is "
        "scale-out pressure; 0 disables the latency input (default "
        "0; elastic/autoscale.py)"),
    "MXTPU_ELASTIC_COOLDOWN_SEC": (
        "minimum seconds between a scale event and the next "
        "scale-in — hysteresis so bursty load cannot flap the fleet "
        "(default 30; elastic/autoscale.py)"),
    "MXTPU_LEND_DEADLINE_SEC": (
        "device-lending lease deadline: chips borrowed from training "
        "for serving are due back after this many seconds — a "
        "borrower that has not returned (or never reported ready) by "
        "then is revoked and the chips reshape back into training "
        "(default 60; cluster/lending.py)"),
    "MXTPU_LEND_MIN_TRAIN_DP": (
        "training dp floor for device lending: a lend that would "
        "shrink the ElasticTrainer below this many shards is refused "
        "(default 1; cluster/lending.py)"),
    "MXTPU_LEND_RECLAIM_BACKOFF_MS": (
        "total backoff budget for one lend/reclaim protocol leg: "
        "bounds the step-boundary quiesce wait, reshape retries, and "
        "how much of an injected reclaim_timeout borrower drain is "
        "honored (default 5000; cluster/lending.py)"),
    "MXTPU_LOCK_WITNESS": (
        "set to 1 to patch the framework's lock constructors with the "
        "dynamic lock-order witness: every acquisition edge and "
        "held-across-Condition.wait hazard is recorded and dumped as a "
        "lockgraph artifact at exit (default 0; analysis/witness.py)"),
    "MXTPU_LOCK_WITNESS_PATH": (
        "where the lock witness writes its lockgraph JSON artifact at "
        "process exit (default ./lockgraph.json; analysis/witness.py)"),
    "MXTPU_TIMELINE_WINDOW": (
        "frames the in-process metric timeline retains: each tick "
        "records one registry snapshot into a bounded ring and the "
        "oldest frame past this cap is evicted (default 128; "
        "telemetry/timeline.py)"),
    "MXTPU_TIMELINE_SEC": (
        "period of the timeline's background frame recorder: > 0 "
        "starts a daemon that ticks the process timeline every this "
        "many seconds when telemetry is enabled; <= 0 leaves ticking "
        "explicit (default 0; telemetry/timeline.py)"),
    "MXTPU_SLO_FILE": (
        "JSON file declaring the SLO objectives the burn-rate tracker "
        "evaluates (a list of objective dicts, same keys as "
        "slo.DEFAULT_OBJECTIVES); unset uses the built-in inter-token "
        "p99 / e2e p99 / rejection-rate trio (default unset; "
        "telemetry/slo.py)"),
    "MXTPU_TAIL_ENABLE": (
        "1 = the serving schedulers stamp per-request critical-path "
        "decision events and the tail joiner attributes them; 0 "
        "disables the whole tail-attribution plane (default 1; "
        "profiling/tailpath.py, docs/observability.md)"),
    "MXTPU_TAIL_WINDOW": (
        "completed requests the tail aggregator retains in its "
        "sliding window before the oldest is evicted (default 512; "
        "profiling/tailpath.py)"),
    "MXTPU_TAIL_SLOW_FRAC": (
        "fraction of the windowed requests treated as the slow "
        "cohort whose blame bins rank the tail drivers (default 0.1 "
        "= slowest decile; profiling/tailpath.py)"),
    "MXTPU_TAIL_ARTIFACT": (
        "path a tail/v1 attribution artifact is dumped to by "
        "consumers that honor it (serving_bench --tail-json "
        "overrides; default unset = no auto-dump; "
        "profiling/tailpath.py, tools/serving_bench.py)"),
}


def env_vars():
    """{name: (current value or None, description)} for every honored
    environment variable."""
    return {k: (os.environ.get(k), v) for k, v in _ENV_VARS.items()}


def find_lib_path():
    """Paths of the built native libraries (ref: libinfo.py
    find_lib_path — there it locates libmxnet.so; here the runtime is
    jax + the _native components)."""
    here = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "_native")
    return sorted(
        os.path.join(here, f) for f in os.listdir(here)
        if f.endswith(".so"))


class Feature:
    def __init__(self, name, enabled, detail=""):
        self.name = name
        self.enabled = bool(enabled)
        self.detail = detail

    def __repr__(self):
        mark = "✔" if self.enabled else "✖"
        return f"{mark} {self.name}" + (f" ({self.detail})"
                                        if self.detail else "")


def features():
    """Runtime feature flags (ref: python/mxnet/runtime.py Features —
    there compile-time USE_* flags; here what this host can actually
    do)."""
    import jax

    feats = []
    try:
        devs = jax.devices()
        plat = devs[0].platform
    except Exception:  # noqa: BLE001 — backend init can fail headless
        devs, plat = [], "none"
    feats.append(Feature("TPU", plat == "tpu" or plat == "axon",
                         f"{len(devs)} x {plat}"))
    feats.append(Feature("MULTI_DEVICE", len(devs) > 1,
                         f"{len(devs)} devices"))
    from .base import get_env
    feats.append(Feature("NAIVE_ENGINE",
                         get_env("MXNET_ENGINE_TYPE", "") == "NaiveEngine"))

    def _native_ok(loader):
        try:
            return loader() is not None
        except Exception:  # noqa: BLE001 — missing toolchain/headers
            return False

    from . import _native
    feats.append(Feature("NATIVE_CORE", _native_ok(_native.load_core),
                         "host storage pool + dependency engine"))
    feats.append(Feature("NATIVE_COMM", _native_ok(_native.load_comm),
                         "TCP parameter-server transport"))
    feats.append(Feature("NATIVE_IMGDEC", _native_ok(_native.load_imgdec),
                         "libjpeg batch decoder"))
    try:
        import PIL  # noqa: F401
        feats.append(Feature("PIL", True))
    except ImportError:
        feats.append(Feature("PIL", False))
    return feats
