"""Parameter-server entry point (ref: python/mxnet/kvstore_server.py —
the process `tools/launch.py` starts in the server role enters this
loop; the reference reads DMLC_ROLE and blocks in ps-lite's server).

Here the server loop lives in the native transport
(`kvstore/dist.py run_server` over `_native/comm.cc`); this module
keeps the reference's import-level contract so `python -c "import
mxnet_tpu; mxnet_tpu.kvstore_server._init_kvstore_server_module()"`
behaves like the reference server bootstrap."""
from __future__ import annotations

import os


def _init_kvstore_server_module():
    """Enter the server loop when this process holds the server role
    (ref: kvstore_server.py _init_kvstore_server_module)."""
    role = os.environ.get("DMLC_ROLE", "")
    if role == "server":
        from .kvstore import dist
        dist.run_server()
    # worker/scheduler roles fall through exactly like the reference


if __name__ == "__main__":
    _init_kvstore_server_module()
