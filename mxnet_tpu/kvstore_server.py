"""Parameter-server entry point (ref: python/mxnet/kvstore_server.py —
the process `tools/launch.py` starts in the server role enters this
loop; the reference reads DMLC_ROLE and blocks in ps-lite's server).

Here the server loop lives in the native transport
(`kvstore/dist.py run_server` over `_native/comm.cc`); this module
keeps the reference's import-level contract: the reference runs
``_init_kvstore_server_module()`` AT MODULE IMPORT, so ``import mxnet``
inside a ``DMLC_ROLE=server`` process blocks in the server loop and
exits without ever returning to user code (ref:
python/mxnet/kvstore_server.py:90 — module-level call, then
``sys.exit()``). Third-party trackers rely on that: their server
command is just any script that imports the library. The same happens
here via the ``mxnet_tpu/__init__`` import of this module (advisor r4
finding). Set ``MXTPU_NO_SERVER_AUTOINIT=1`` to import the library in
a server-role process without entering the loop (no reference
equivalent; useful for tooling that inspects a server environment)."""
from __future__ import annotations

import os
import sys


def _init_kvstore_server_module():
    """Enter the server loop when this process holds the server role,
    then exit the process (ref: kvstore_server.py
    _init_kvstore_server_module — `server.run(); sys.exit()`)."""
    role = os.environ.get("DMLC_ROLE", "")
    if role == "server":
        from .kvstore import dist
        dist.run_server()
        sys.exit()
    # worker/scheduler roles fall through exactly like the reference


if (os.environ.get("DMLC_ROLE") == "server"
        and os.environ.get("DMLC_PS_ROOT_PORT")
        and not os.environ.get("MXTPU_NO_SERVER_AUTOINIT")):
    # import-time entry, reference contract; gated on the tracker env
    # actually being present so a stray DMLC_ROLE can't hang an import
    _init_kvstore_server_module()


if __name__ == "__main__":
    _init_kvstore_server_module()
