"""Device context — the seam where ``mx.tpu()`` lives.

TPU-native analogue of the reference Context (ref: include/mxnet/base.h:133-159,
python/mxnet/context.py). Device types: cpu, gpu (alias kept for API parity),
tpu (the native accelerator of this framework). A Context resolves to a concrete
``jax.Device``; under the virtual CPU mesh used by tests, ``tpu(i)`` resolves to
the i-th default-backend device so the same model code runs everywhere.
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

_context_stack = threading.local()


class Context:
    """Execution device. Use via mx.cpu() / mx.gpu() / mx.tpu()."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "cpu_shared", 5: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type = device_type.device_type
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_type = device_type
            self.device_id = device_id

    @property
    def device_typeid(self):
        return self.devstr2type[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- resolution to a concrete jax device ------------------------------
    @property
    def jax_device(self):
        """Resolve to a jax.Device.

        ``tpu``/``gpu`` map onto the default accelerator backend; when that
        backend is absent (e.g. CPU-only test runs with a virtual mesh) they
        fall back to the default platform so models are device-portable.
        """
        devs = jax.devices()
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                cpus = devs
            return cpus[min(self.device_id, len(cpus) - 1)]
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self}: only {len(devs)} device(s) visible to the runtime"
            )
        return devs[self.device_id]

    def empty_cache(self):
        # PJRT owns the device allocator; nothing to flush explicitly.
        return


    @classmethod
    def default_ctx(cls):
        if not hasattr(cls._default_ctx, "value"):
            cls._default_ctx.value = Context("cpu", 0)
        return cls._default_ctx.value

    def __enter__(self):
        if not hasattr(_context_stack, "stack"):
            _context_stack.stack = []
        _context_stack.stack.append(Context.default_ctx())
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = _context_stack.stack.pop()
        return False


def dp_mesh(ctx_list):
    """A 1-axis 'dp' Mesh over a context list, or None when the entries
    don't resolve to distinct jax devices (cpu(0) listed twice,
    oversubscribed ids). Shared by Module binding and gluon
    split_and_load so both agree on what forms a data-parallel mesh."""
    import numpy as np
    from jax.sharding import Mesh

    devices = [Context(c).jax_device for c in ctx_list]
    if len(set(devices)) != len(devices):
        return None
    return Mesh(np.array(devices), ("dp",))


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    return Context("gpu", device_id)


def tpu(device_id=0):
    """The native accelerator context of this framework (north star: mx.tpu())."""
    return Context("tpu", device_id)


def current_context():
    return Context.default_ctx()


def num_gpus():
    try:
        return len(jax.devices("gpu"))
    except RuntimeError:
        return 0


def num_tpus():
    try:
        plat = jax.default_backend()
        if plat == "cpu":
            return 0
        return len(jax.devices())
    except RuntimeError:
        return 0


def ctx_from_jax_device(dev):
    plat = getattr(dev, "platform", "cpu")
    if plat == "cpu":
        return Context("cpu", dev.id)
    if plat == "gpu":
        return Context("gpu", dev.id)
    return Context("tpu", dev.id)
