"""Deprecated learning-rate schedulers kept for old scripts (ref:
python/mxnet/misc.py — the pre-lr_scheduler API; new code uses
mxnet_tpu.lr_scheduler)."""
from __future__ import annotations

import logging
import math

from .base import MXNetError


class LearningRateScheduler:
    """Base class (ref: misc.py LearningRateScheduler)."""

    def __init__(self):
        self.base_lr = 0.01

    def __call__(self, iteration):
        raise NotImplementedError("must override this")


class FactorScheduler(LearningRateScheduler):
    """lr = base_lr * factor^(iteration // step)
    (ref: misc.py FactorScheduler)."""

    def __init__(self, step, factor=0.1):
        super().__init__()
        if step < 1:
            raise MXNetError("Schedule step must be >= 1")
        if factor >= 1.0:
            raise MXNetError("Factor must be < 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.old_lr = self.base_lr
        self.init = False

    def __call__(self, iteration):
        if not self.init:
            self.init = True
            self.old_lr = self.base_lr
        lr = self.base_lr * math.pow(self.factor,
                                     int(iteration / self.step))
        if lr != self.old_lr:
            self.old_lr = lr
            logging.info("At Iteration [%d]: Switch to new learning "
                         "rate %.5f", iteration, lr)
        return lr
