"""Legacy multi-device execution helpers (ref: python/mxnet/
executor_manager.py — the pre-Module data-parallel machinery; Module/
Gluon replaced it, but `_split_input_slice` remains a public helper
old training scripts import)."""
from __future__ import annotations

from .base import MXNetError


def _split_input_slice(batch_size, work_load_list):
    """Slice a batch across devices proportionally to work loads
    (ref: executor_manager.py:33 _split_input_slice)."""
    total = sum(work_load_list)
    if total <= 0:
        raise MXNetError("Invalid workload")
    slices = []
    start = 0
    for i, load in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * load / total))
        if end > batch_size or end <= start:
            raise MXNetError(
                "Too many slices: some splits are empty for batch "
                "size %d" % batch_size)
        slices.append(slice(start, end))
        start = end
    return slices


def _check_arguments(symbol):
    """Reject duplicate argument/aux names (ref: executor_manager.py
    _check_arguments)."""
    names = symbol.list_arguments()
    if len(set(names)) != len(names):
        dup = sorted(n for n in set(names) if names.count(n) > 1)
        raise MXNetError(f"Find duplicated argument name {dup}")
    aux = symbol.list_auxiliary_states()
    if len(set(aux)) != len(aux):
        dup = sorted(n for n in set(aux) if aux.count(n) > 1)
        raise MXNetError(f"Find duplicated auxiliary name {dup}")
