"""Trainer (ref: python/mxnet/gluon/trainer.py:27).

Applies an Optimizer to a set of Parameters. The reference wires kvstore
Reduce/Broadcast between devices; here single-host multi-device DP runs
through the sharded jit step (parallel.data_parallel) and the kvstore seam is
kept for the update_on_kvstore policy and the dist/sparse paths.
"""
from __future__ import annotations

from .. import optimizer as opt_mod
from .. import tracing as _tracing
from ..base import MXNetError
from ..telemetry import step as _tm_step
from .parameter import Parameter, ParameterDict


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a list/dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p}")
            self._param2idx[p.name] = i
            self._params.append(p)
        optimizer_params = optimizer_params or {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._optimizer = opt_mod.create(optimizer, param_idx2name={
            i: p.name for i, p in enumerate(self._params)},
            **optimizer_params)
        self._updaters = opt_mod.get_updater(self._optimizer)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._compression_params = compression_params
        self._kv_initialized = False
        self._params_to_init = list(self._params)

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        from ..kvstore.kvstore import KVStore as _KVStore
        if isinstance(self._kvstore_type, _KVStore):
            # the reference accepts a ready KVStore instance as well as a
            # type string (gluon/trainer.py _init_kvstore)
            self._kvstore = self._kvstore_type
        elif self._kvstore_type and self._kvstore_type != "None" and \
                str(self._kvstore_type).startswith("dist"):
            from .. import kvstore as kv_mod
            self._kvstore = kv_mod.create(self._kvstore_type)
        if self._kvstore is not None:
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = True
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                if p._data is not None:
                    self._kvstore.init(i, p.data())
                    if not self._update_on_kvstore:
                        # worker-side updates never pull params from the
                        # store afterwards, so the init broadcast (rank
                        # 0's values) must land here or replicas diverge
                        # from their own random inits (ref: trainer.py
                        # _init_params pulls after init)
                        self._kvstore.pull(i, out=p.data())
        self._kv_initialized = True

    def step(self, batch_size, ignore_stale_grad=False):
        """rescale by 1/batch_size, allreduce (mesh DP: already summed by
        psum in the sharded step), update."""
        # rescale BEFORE kvstore init: update_on_kvstore pickles the
        # optimizer to the server on first step, and the server must see
        # the batch scaling or dist updates explode by batch_size
        self._optimizer.rescale_grad = self._scale / batch_size
        # root span per optimizer step: the comm/compute children under
        # it are what trace_merge's straggler report groups by step
        n = self._step_count = getattr(self, "_step_count", -1) + 1
        from ..profiling import health as _health
        with _tracing.span("trainer_step", cat="step", step=n) as sp:
            try:
                if not self._kv_initialized:
                    self._init_kvstore()
                self._sync_server_rescale()
                self._allreduce_grads()
                self._update(ignore_stale_grad)
            except Exception as e:
                # an allocation failure mid-step leaves the combined
                # memory postmortem (ranked buffers + census + flight
                # dump) before propagating
                from ..profiling import memory as _mem
                _mem.maybe_oom_postmortem(e, source="trainer_step")
                raise
            # health boundary INSIDE the step span: lagged loss-EWMA /
            # grad-norm / nonfinite attrs land on the span so
            # trace_merge can name the rank that went unhealthy; a
            # MXTPU_HEALTH=raise trip surfaces here, at the boundary
            _health.step_boundary("trainer", span=sp)
        # one boundary per optimizer step: charges the data/comm/compile
        # time accumulated since the previous step to this one
        # (telemetry/step.py; wall-clock only, no host sync). Manual
        # loops with long gaps between steps (eval phases, user pauses)
        # should call telemetry.step.reset() at loop start so the first
        # interval doesn't span the gap — Module.fit does this per epoch
        _tm_step.step_boundary("trainer")

    def _sync_server_rescale(self):
        """Re-ship the optimizer when the batch scale changes after the
        first step (e.g. a short final batch) — the server-side updater
        would otherwise keep applying the stale rescale_grad."""
        if self._kvstore is None or not self._update_on_kvstore:
            return
        shipped = getattr(self, "_shipped_rescale", None)
        if shipped is None:
            self._shipped_rescale = self._optimizer.rescale_grad
        elif shipped != self._optimizer.rescale_grad:
            self._kvstore.set_optimizer(self._optimizer)
            self._shipped_rescale = self._optimizer.rescale_grad

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is not None and not self._update_on_kvstore:
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.push(i, p.grad())
                    self._kvstore.pull(i, p.grad())

    def update(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        from ..profiling import health as _health
        # one probe per step: the post-allreduce gradients, updated
        # weights and (for update-to-weight ratios) the pre-update
        # weights — updates are functional, so the old array stays
        # reachable with no copy. commit() is ONE cached jitted
        # dispatch covering the sentry counts AND the norm telemetry;
        # the per-call Updater check is suppressed underneath it.
        probe = _health.step_probe()
        with _health.updater_covered():
            for i, p in enumerate(self._params):
                if p.grad_req == "null":
                    continue
                if p._data is None:
                    if not ignore_stale_grad:
                        raise MXNetError(
                            f"parameter {p.name} not initialized "
                            "before step()")
                    continue
                # pre-update weights only when the probe computes
                # update ratios: with MXTPU_HEALTH_NORMS=0 holding
                # them would pin a superseded copy of every weight
                # through the loop for nothing
                old = p.data()._data if probe is not None \
                    and probe.wants_norms else None
                if self._kvstore is not None and \
                        self._update_on_kvstore:
                    self._kvstore.push(i, p.grad())
                    self._kvstore.pull(i, p.data())
                else:
                    self._updaters(i, p.grad(), p.data())
                if probe is not None:
                    probe.add(p.name, p.data(), p.grad(),
                              weight_before=old)
        if probe is not None:
            probe.commit()

    def save_states(self, fname):
        """Optimizer state checkpoint (ref: trainer.py save_states). When the
        optimizer runs on the kvstore, the live state is the kvstore's
        Updater, not the local one. Written atomically with a CRC
        manifest entry (checkpoint.atomic_write) so a preemption
        mid-write can never leave a torn .states file."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is not None and self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=False)
        else:
            from ..checkpoint import atomic_write
            with atomic_write(fname) as f:
                f.write(self._updaters.get_states())

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is not None and self._update_on_kvstore:
            # no verify here: kvstore.load_optimizer_states CRC-checks
            # the same file — doing it twice doubles the resume I/O
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
            # mirror the loaded state into the LOCAL updater too: a
            # later fall back to local update (kvstore torn down,
            # update_on_kvstore flipped off) must not resume from the
            # stale pre-load state it would otherwise still hold
            self._updaters.set_states(
                self._kvstore._updater.get_states(dump_optimizer=False))
            self._updaters.optimizer = self._optimizer
        else:
            from ..checkpoint import verify
            verify(fname)
            with open(fname, "rb") as f:
                self._updaters.set_states(f.read())
            # set_states may swap in a pickled optimizer (states dumped
            # with dump_optimizer=True); keep the trainer's handle — and
            # with it set_learning_rate() — pointed at the live object
            self._optimizer = self._updaters.optimizer
