"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split batch of {size} into {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        if batch_axis == 0:
            slices.append(data[begin:end])
        else:
            from ..ndarray import slice_axis
            slices.append(slice_axis(data, axis=batch_axis, begin=begin,
                                     end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Lay the batch out across the contexts (ref: utils.py
    split_and_load).

    The reference returns one slice per device and runs K separate
    forward/backwards. The TPU-native equivalent is SPMD: the batch is
    placed ONCE, sharded over a 'dp' mesh built from ``ctx_list``, and
    returned as a single-element list — the usual
    ``for x in split_and_load(...)`` loop then runs one XLA program over
    all devices, with the gradient all-reduce inserted by the
    partitioner instead of KVStore Reduce (SURVEY.md §7 design stance).
    """
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    from ..context import dp_mesh
    uneven = data.shape[batch_axis] % len(ctx_list) != 0
    if even_split and uneven:
        raise MXNetError(
            f"cannot evenly split batch of {data.shape[batch_axis]} "
            f"across {len(ctx_list)} devices")
    mesh = None if uneven else dp_mesh(ctx_list)
    if mesh is None:
        # repeated devices can't form a mesh, and GSPMD needs the batch
        # axis divisible by the mesh — plain slicing for parity in both
        # cases (the reference's uneven [3,3,2,2]-style slices)
        slices = split_data(data, len(ctx_list), batch_axis, even_split)
        return [s.as_in_context(ctx)
                for s, ctx in zip(slices, ctx_list)]
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P(*([None] * batch_axis + ["dp"]))
    return [NDArray(jax.device_put(data._data,
                                   NamedSharding(mesh, spec)))]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the joint L2 norm <= max_norm (ref: utils.py)."""
    total = 0.0
    for a in arrays:
        n = float((a * a).sum().asscalar())
        total += n
    total = np.sqrt(total)
    if check_isfinite and not np.isfinite(total):
        import warnings
        warnings.warn("nan or inf found in gradients")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    raise MXNetError("this environment has no network egress; place files "
                     "locally and load them directly")
