"""Basic neural network layers (ref: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as np

from ... import autograd
from ..block import Block, HybridBlock, defer_aux_update


class Sequential(Block):
    """Stack of blocks executed sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = self.__class__(prefix=self._prefix)
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        # Sequential of HybridBlocks: children hybridize individually
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)
        return self

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = self.__class__(prefix=self._prefix)
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (ref: basic_layers.py Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation, prefix=activation + "_") \
                if activation else None

    def infer_shape(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape_inferred((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation  # before super(): _alias() reads it
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type if isinstance(self._act_type, str) else "activation"

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with functional running-stat updates
    (ref: basic_layers.py BatchNorm; op src/operator/nn/batch_norm.cc)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if axis == 1:
            # inside an nn.layout_scope("NHWC") the default channel axis
            # follows the scope's channel-last convention
            from .conv_layers import active_layout
            if active_layout():
                axis = -1
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,), grad_req="null",
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,), grad_req="null",
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape_inferred((c,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ...symbol.symbol import Symbol
        if isinstance(x, Symbol):
            # symbolic trace: emit one neutral BatchNorm node — the
            # executor decides training vs inference at run time and
            # materializes the moving-stat updates itself
            return F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                eps=self._epsilon, momentum=self._momentum,
                fix_gamma=not self._scale,
                use_global_stats=self._use_global_stats, axis=self._axis)
        training = autograd.is_training() and not self._use_global_stats
        if training:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                eps=self._epsilon, momentum=self._momentum,
                fix_gamma=not self._scale,
                use_global_stats=False, output_mean_var=True,
                axis=self._axis, training=True)
            m = self._momentum
            defer_aux_update(self.running_mean,
                             running_mean * m + mean * (1 - m))
            defer_aux_update(self.running_var,
                             running_var * m + var * (1 - m))
            return out
        return F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=True, axis=self._axis, training=False)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (ref: src/operator/contrib/sync_batch_norm.cc).

    Under pjit/shard_map data parallelism the batch axis is sharded and XLA's
    reduction over it already spans devices, so plain BatchNorm inside a
    sharded step IS sync-BN; this subclass exists for API parity."""

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        kwargs.pop("ndev", None)
        super().__init__(in_channels=in_channels, **kwargs)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape_inferred((c,))
        self.beta.shape_inferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape_inferred((c,))
        self.beta.shape_inferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    def __init__(self, function, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(function, str):
            from ... import ndarray as F
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._func_name = function if isinstance(function, str) else None
        self._func = function

    def hybrid_forward(self, F, *args):
        if self._func_name is not None:
            return getattr(F, self._func_name)(*args)
        return self._func(F, *args)
