"""Convolution / pooling layers (ref: python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

import threading

from ...base import MXNetError
from ..block import HybridBlock
from .basic_layers import Activation


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,) * n


_layout_tls = threading.local()
_NC_FIRST = {"NCW", "NCHW", "NCDHW"}
_CHANNEL_LAST_BY_ND = {1: "NWC", 2: "NHWC", 3: "NDHWC"}


class layout_scope:
    """Build layers channel-last without per-layer layout arguments.

    TPU convolutions want C on the 128-lane minor dimension; inside
    ``with nn.layout_scope("NHWC"):`` every Conv/Pool layer constructed
    with the default NC-first layout switches to the channel-last layout
    of its rank, and BatchNorm's default axis=1 becomes axis=-1. This is
    the construction-time analogue of the reference's MKL-DNN opaque
    layouts (ref: src/ndarray/ndarray.cc:389 GetMKLDNNData — the
    accelerator gets its preferred layout; the graph edges stay in the
    user's NCHW convention via one transpose at the model stem)."""

    def __init__(self, layout="NHWC"):
        if layout not in ("NHWC", "NWC", "NDHWC", None):
            raise MXNetError(f"layout_scope: unsupported {layout!r}")
        self._layout = layout

    def __enter__(self):
        self._prev = getattr(_layout_tls, "value", None)
        _layout_tls.value = self._layout
        return self

    def __exit__(self, *exc):
        _layout_tls.value = self._prev
        return False


def active_layout():
    return getattr(_layout_tls, "value", None)


def _resolve_layout(layout, nd):
    """Switch a defaulted NC-first layout to channel-last when a
    channel-last layout_scope is active (explicit layouts win)."""
    if active_layout() and layout in _NC_FIRST:
        return _CHANNEL_LAST_BY_ND[nd]
    return layout


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution", adj=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        layout = _resolve_layout(layout, ndim)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        self._groups = groups
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels else 0) \
                    + kernel_size
            else:  # Deconvolution: (in_channels, channels//groups, *k)
                wshape = (in_channels, channels // groups) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation) if activation else None

    def infer_shape(self, x, *args):
        c = x.shape[self._kwargs["layout"].index("C")]
        if self._op_name == "Convolution":
            self.weight.shape_inferred(
                (self._channels, c // self._groups) + self._kwargs["kernel"])
        else:
            self.weight.shape_inferred(
                (c, self._channels // self._groups) + self._kwargs["kernel"])
        if self.bias is not None:
            self.bias.shape_inferred((self._channels,))

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         prefix=prefix, params=params)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         prefix=prefix, params=params)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         prefix=prefix, params=params)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_pair(output_padding, 1),
                         prefix=prefix, params=params)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_pair(output_padding, 2),
                         prefix=prefix, params=params)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_pair(output_padding, 3),
                         prefix=prefix, params=params)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        layout = _resolve_layout(layout, len(pool_size))
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "pool_type": pool_type, "global_pool": global_pool,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if layout and "C" in layout and not layout.startswith("NC"):
            self._kwargs["layout"] = layout
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, prefix=None, params=None):
        super().__init__(_pair(pool_size, 1),
                         None if strides is None else _pair(strides, 1),
                         _pair(padding, 1), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_pair(pool_size, 2),
                         None if strides is None else _pair(strides, 2),
                         _pair(padding, 2), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_pair(pool_size, 3),
                         None if strides is None else _pair(strides, 3),
                         _pair(padding, 3), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, prefix=None,
                 params=None):
        super().__init__(_pair(pool_size, 1),
                         None if strides is None else _pair(strides, 1),
                         _pair(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_pair(pool_size, 2),
                         None if strides is None else _pair(strides, 2),
                         _pair(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_pair(pool_size, 3),
                         None if strides is None else _pair(strides, 3),
                         _pair(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "max", layout,
                         prefix=prefix, params=params)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "max", layout,
                         prefix=prefix, params=params)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max",
                         layout, prefix=prefix, params=params)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "avg", layout,
                         prefix=prefix, params=params)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", layout,
                         prefix=prefix, params=params)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg",
                         layout, prefix=prefix, params=params)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        p = _pair(padding, 4) if isinstance(padding, (tuple, list)) \
            else (padding,) * 4
        self._padding = (0, 0, 0, 0) + tuple(p)

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
