from .basic_layers import (Activation, BatchNorm, Dense, Dropout, ELU,
                           Embedding, Flatten, GELU, HybridLambda,
                           HybridSequential, InstanceNorm, Lambda, LayerNorm,
                           LeakyReLU, PReLU, SELU, Sequential, Swish,
                           SyncBatchNorm)
from .conv_layers import (AvgPool1D, AvgPool2D, AvgPool3D, Conv1D,
                          Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
                          Conv3DTranspose, GlobalAvgPool1D, GlobalAvgPool2D,
                          GlobalAvgPool3D, GlobalMaxPool1D, GlobalMaxPool2D,
                          GlobalMaxPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
                          ReflectionPad2D, active_layout, layout_scope)
from ..block import Block, HybridBlock, SymbolBlock
