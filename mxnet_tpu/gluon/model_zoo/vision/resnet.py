"""ResNet V1/V2 (ref: python/mxnet/gluon/model_zoo/vision/resnet.py).

Same architecture family as the reference zoo (BasicBlock/Bottleneck x
{18,34,50,101,152}); this is the flagship benchmark model (BASELINE.md
ResNet-50). bfloat16 inference casts activations while keeping BN in fp32.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn
from ...nn import conv_layers as _conv_layers


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(nn.Conv2D(channels, 3, stride, 1,
                                    use_bias=False, in_channels=in_channels))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                                    in_channels=channels))
            self.body.add(nn.BatchNorm())
            if downsample:
                self.downsample = nn.HybridSequential(prefix="")
                self.downsample.add(nn.Conv2D(channels, 1, stride,
                                              use_bias=False,
                                              in_channels=in_channels))
                self.downsample.add(nn.BatchNorm())
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(nn.Conv2D(channels // 4, 1, stride, use_bias=False))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(channels // 4, 3, 1, 1, use_bias=False))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(channels, 1, 1, use_bias=False))
            self.body.add(nn.BatchNorm())
            if downsample:
                self.downsample = nn.HybridSequential(prefix="")
                self.downsample.add(nn.Conv2D(channels, 1, stride,
                                              use_bias=False,
                                              in_channels=in_channels))
                self.downsample.add(nn.BatchNorm())
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bn1 = nn.BatchNorm()
            self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                                   in_channels=in_channels)
            self.bn2 = nn.BatchNorm()
            self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                                   in_channels=channels)
            if downsample:
                self.downsample = nn.Conv2D(channels, 1, stride,
                                            use_bias=False,
                                            in_channels=in_channels)
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bn1 = nn.BatchNorm()
            self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False)
            self.bn2 = nn.BatchNorm()
            self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1,
                                   use_bias=False)
            self.bn3 = nn.BatchNorm()
            self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False)
            if downsample:
                self.downsample = nn.Conv2D(channels, 1, stride,
                                            use_bias=False,
                                            in_channels=in_channels)
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


def _layout_build_scope(layout):
    """Constructing with layout="NHWC" must build every conv/pool/BN in
    the subtree channel-last — resolve the scope HERE so direct class
    construction works, not only the get_resnet factory."""
    from contextlib import nullcontext
    return nn.layout_scope("NHWC") if layout == "NHWC" else nullcontext()


class S2DStemConv(HybridBlock):
    """7x7/s2 stem conv computed in space-to-depth form (MLPerf ResNet
    TPU recipe — see ops/nn.py s2d_stem_conv). Holds the SAME
    (O, C, 7, 7) OIHW weight a standard stem Conv2D would, so
    checkpoints interoperate; only the compute layout differs."""

    def __init__(self, channels, in_channels=3, block=2, **kwargs):
        super().__init__(**kwargs)
        self._block = block
        self._layout = _conv_layers.active_layout() or "NCHW"
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, in_channels, 7, 7),
                init=None, allow_deferred_init=False)

    def hybrid_forward(self, F, x, weight):
        return F._contrib_s2d_stem_conv(
            x, weight, stride=2, pad=3, block=self._block,
            layout=self._layout)


def _stem_layers(stem, channels0):
    """The reference's 7x7 stem, optionally in space-to-depth form."""
    if stem == "s2d":
        # explicit Conv2D-convention prefix: the stem weight must be
        # named <net>_conv2d0_weight exactly like the standard stem's
        # auto-named Conv2D, or load_parameters/pretrained checkpoints
        # cannot cross stems (stage convs live in stage*_ scopes, so
        # the bare conv2d0_ name stays collision-free)
        conv = S2DStemConv(channels0, prefix="conv2d0_")
    elif stem == "standard":
        conv = nn.Conv2D(channels0, 7, 2, 3, use_bias=False)
    else:
        raise MXNetError(f"unknown stem {stem!r}")
    return [conv, nn.BatchNorm(), nn.Activation("relu"),
            nn.MaxPool2D(3, 2, 1)]


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", stem="standard", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._data_layout = layout
        with _layout_build_scope(layout), self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                            use_bias=False))
            else:
                for layer in _stem_layers(stem, channels[0]):
                    self.features.add(layer)
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        if self._data_layout == "NHWC":
            # models always take NCHW user data; one transpose at the
            # graph edge puts the whole internal graph channel-last
            x = F.transpose(x, axes=(0, 2, 3, 1))
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", stem="standard", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._data_layout = layout
        with _layout_build_scope(layout), self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                            use_bias=False))
            else:
                for layer in _stem_layers(stem, channels[0]):
                    self.features.add(layer)
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        if self._data_layout == "NHWC":
            x = F.transpose(x, axes=(0, 2, 3, 1))
        x = self.features(x)
        return self.output(x)


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}

resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    if num_layers not in resnet_spec:
        raise MXNetError(f"invalid resnet depth {num_layers}")
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"resnet{num_layers}_v{version}", root, ctx)
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
