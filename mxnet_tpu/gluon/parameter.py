"""Parameter / ParameterDict (ref: python/mxnet/gluon/parameter.py).

A Parameter owns one NDArray (single logical copy — replication across a
device mesh is a sharding annotation in this framework, not per-context
copies) plus its gradient, init policy, and deferred-shape state.
"""
from __future__ import annotations

import re
from collections import OrderedDict

import numpy as np

from .. import initializer as init_mod
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray, array, zeros


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's data is requested before shape is known."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self.stype = stype
        self.grad_stype = grad_stype
        self._data = None
        self._grad = None
        self._deferred_init = None
        self._ctx = None
        # sharding annotation for pjit'd steps (jax.sharding.PartitionSpec
        # or None = replicated); consumed by parallel.data_parallel
        self.partition_spec = None

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            self._data._grad_req = req
            if req == "null":
                self._grad = None
                self._data.grad = None
            elif self._grad is None:
                self._init_grad()

    def _shape_incomplete(self):
        return self.shape is None or any(d == 0 for d in self.shape)

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        self._ctx = ctx or current_context()
        if self._shape_incomplete():
            if self.allow_deferred_init:
                self._deferred_init = (init, self._ctx, default_init)
                return
            raise MXNetError(
                f"cannot initialize {self.name}: shape {self.shape} incomplete"
                " and deferred init not allowed")
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        from ..profiling import memory as _mem
        data = zeros(self.shape, dtype=self.dtype)
        initializer = init_mod.create(init or self.init or default_init)
        desc = init_mod.InitDesc(self.name)
        initializer(desc, data)
        self._data = _mem.tag_role(data, "parameter")
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if self._shape_incomplete():
            raise DeferredInitializationError(
                f"parameter {self.name} shape still unknown")
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, default_init)

    def _init_grad(self):
        self._data.attach_grad(self._grad_req)
        self._grad = self._data.grad

    def shape_inferred(self, shape):
        """Called by layers once the input-dependent dims are known."""
        shape = tuple(shape)
        if self.shape is not None:
            merged = tuple(
                n if o == 0 else o for o, n in zip(self.shape, shape))
            if len(merged) != len(shape) or any(
                    o != 0 and o != n for o, n in zip(self.shape, shape)):
                if merged != shape:
                    raise MXNetError(
                        f"{self.name}: inferred shape {shape} incompatible "
                        f"with declared {self.shape}")
            self.shape = merged
        else:
            self.shape = shape
        if self._deferred_init is not None:
            self._finish_deferred_init()

    # -- access ------------------------------------------------------------
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name} not fully initialized yet "
                    "(deferred shape)")
            raise MXNetError(
                f"parameter {self.name} has not been initialized; call "
                ".initialize() first")
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        if self._grad is None:
            raise MXNetError(
                f"parameter {self.name} has no gradient (grad_req="
                f"{self._grad_req!r} or not initialized)")
        return self._data.grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self._ctx or current_context()]

    def zero_grad(self):
        if self._data is not None and self._data.grad is not None:
            self._data.grad._data = self._data.grad._data * 0

    def set_data(self, data):
        from ..profiling import memory as _mem
        data = data if isinstance(data, NDArray) else array(data)
        if self.shape is not None and not self._shape_incomplete() and \
                tuple(data.shape) != tuple(self.shape):
            raise MXNetError(
                f"set_data: shape {data.shape} != parameter shape {self.shape}")
        self.shape = tuple(data.shape)
        if self._data is None:
            self._data = data.copy() if isinstance(data, NDArray) else data
            if self._grad_req != "null":
                self._init_grad()
            self._deferred_init = None
        else:
            grad = self._data.grad
            req = self._data._grad_req
            self._data._data = data._data
            self._data.grad = grad
            self._data._grad_req = req
        _mem.tag_role(self._data, "parameter")

    def _load_init(self, data, ctx=None):
        self.set_data(data)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data._data = self._data._data.astype(np.dtype(dtype))

    def var(self):
        from ..symbol import var
        return var(self.name, shape=self.shape, dtype=self.dtype)

    def reset_ctx(self, ctx):
        self._ctx = ctx

    def row_sparse_data(self, row_id):
        from ..ndarray import sparse
        return sparse.row_sparse_array(self.data()).retain(row_id)


class Constant(Parameter):
    """Non-learnable parameter (ref: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        value = value if isinstance(value, NDArray) else array(value)
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=init_mod.Load({name: value}))


class ParameterDict:
    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        items = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict(prefix={self._prefix!r}\n{items}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        """Find prefix+name, creating (or sharing) it if absent."""
        name = self._prefix + name
        if name in self._params:
            param = self._params[name]
            # sharing an existing parameter: declared attrs must agree
            want_shape = kwargs.get("shape")
            if want_shape is not None and param.shape is not None:
                if len(want_shape) != len(param.shape) or any(
                        w != 0 and p != 0 and w != p
                        for w, p in zip(want_shape, param.shape)):
                    raise MXNetError(
                        f"cannot share parameter {name}: requested shape "
                        f"{tuple(want_shape)} != existing {param.shape}")
            want_dtype = kwargs.get("dtype")
            if want_dtype is not None and str(want_dtype) != str(param.dtype):
                raise MXNetError(
                    f"cannot share parameter {name}: requested dtype "
                    f"{want_dtype} != existing {param.dtype}")
            return param
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._params[name]
        param = Parameter(name, **kwargs)
        self._params[name] = param
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = Constant(name, value)
        return self._params[name]

    def update(self, other):
        for k, v in other.items():
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx,
                         default_init=init or init_mod.Uniform(),
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def save(self, fname, strip_prefix=""):
        from ..ndarray import save as nd_save
        payload = {}
        for name, p in self._params.items():
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            payload[name] = p.data()
        nd_save(fname, payload)

    def load(self, fname, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import load as nd_load
        loaded = nd_load(fname)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                p._load_init(loaded[name], ctx)
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing in file {fname}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"file {fname} has extra parameters {extra}")
