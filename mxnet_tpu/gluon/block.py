"""Block / HybridBlock — the Gluon layer API (ref: python/mxnet/gluon/block.py).

Eager mode runs hybrid_forward op-by-op on the PJRT stream (the reference's
imperative engine path). ``hybridize()`` swaps in a CachedOp: the whole
subtree is traced once into a single jax.jit computation with parameters as
traced arguments — the TPU-native equivalent of the reference's
_build_cache -> ndarray.CachedOp(static_alloc) (block.py:748-785), with XLA
buffer assignment replacing the static memory plan. BatchNorm-style aux-state
updates are collected during the trace and returned as extra outputs
(functional state threading instead of in-place mutation).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax

from .. import autograd
from .. import ndarray as nd_mod
from .. import random as _random
from ..base import MXNetError
from ..ndarray import NDArray
from .parameter import (DeferredInitializationError, Parameter, ParameterDict)

_naming = threading.local()


class _BlockScope:
    """Hierarchical name scope (ref: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                if not hasattr(_naming, "counter"):
                    _naming.counter = {}
                count = _naming.counter.get(hint, 0)
                _naming.counter[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, shared=parent._shared)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return False
        _BlockScope._current.value = self._old_scope
        return False


# thread-local collector for functional aux-state updates during jit tracing
_aux_updates = threading.local()


def defer_aux_update(param, new_value):
    """BatchNorm-style running-stat update: collected when tracing (returned
    as jit outputs and written back after execution), applied directly in
    eager mode."""
    stack = getattr(_aux_updates, "stack", None)
    if stack:
        stack[-1].append((param, new_value))
    else:
        if not isinstance(new_value, NDArray):
            # symbolic trace: aux updates are materialized by the
            # executor's BatchNorm training hook, not recorded here
            return
        if param._data is None:
            param.set_data(new_value)
        else:
            param._data._data = new_value._data


def _flatten(args):
    """Flatten nested (lists/tuples of) NDArrays; returns flat list + spec."""
    if isinstance(args, NDArray):
        return [args], "0"
    if isinstance(args, (list, tuple)):
        flat, specs = [], []
        for a in args:
            f, s = _flatten(a)
            flat.extend(f)
            specs.append(s)
        return flat, ("t", type(args).__name__, specs)
    return [args], "raw"


def _regroup(flat, spec):
    if spec == "0":
        return flat.pop(0)
    if spec == "raw":
        return flat.pop(0)
    _, tname, specs = spec
    out = [_regroup(flat, s) for s in specs]
    return tuple(out) if tname == "tuple" else out


class Block:
    """Base building block (ref: gluon/block.py:127)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @property
    def params(self):
        return self._params

    def name_scope(self):
        return self._scope

    def __repr__(self):
        s = f"{self.__class__.__name__}(\n"
        for key, child in self._children.items():
            s += f"  ({key}): {child!r}\n"
        return s + ")"

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self._params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self._params.items()
                        if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        payload = {k: v.data() for k, v in params.items()
                   if v._data is not None}
        nd_mod.save(filename, payload)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        loaded = nd_mod.load(filename)
        params = self._collect_params_with_prefix()
        if not any("." in k for k in loaded) and any("." in k for k in params):
            # file saved with flat prefixed names; match by parameter name
            by_name = {p.name: p for p in params.values()}
            for k, v in loaded.items():
                if k in by_name:
                    by_name[k]._load_init(v, ctx)
                elif not ignore_extra:
                    raise MXNetError(f"unknown parameter {k} in {filename}")
            if not allow_missing:
                missing = set(by_name) - set(loaded)
                if missing:
                    raise MXNetError(
                        f"parameters {sorted(missing)} missing in {filename}")
            return
        for k, p in params.items():
            if k in loaded:
                p._load_init(loaded[k], ctx)
            elif not allow_missing:
                raise MXNetError(f"parameter {k} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"extra parameters in {filename}: {extra}")

    save_params = save_parameters
    load_params = load_parameters

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def zero_grad(self):
        self.collect_params().zero_grad()

    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        out = self(*inputs)
        nparams = sum(
            int(p.data().size) for p in self.collect_params().values()
            if p._data is not None)
        print(f"{self.__class__.__name__}: {nparams} parameters, "
              f"output {[o.shape for o in (out if isinstance(out, (list, tuple)) else [out])]}")
        return out


class HybridBlock(Block):
    """Block that can be traced into a single compiled computation
    (ref: gluon/block.py:671)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_jit = {}
        self._cached_plist = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, forward_bulk_size=None,
                  backward_bulk_size=None):
        self._active = active
        self._flags = {"static_alloc": static_alloc,
                       "static_shape": static_shape}
        self._cached_jit = {}
        self._cached_plist = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from input shapes. Built-in
        layers override; custom blocks with fully-specified shapes never
        need it."""
        raise MXNetError(
            f"{self.__class__.__name__} has deferred-shape parameters but "
            "does not implement infer_shape; give explicit in_units/"
            "in_channels or implement infer_shape")

    def _collect_param_values(self, *args):
        override = getattr(_param_override, "map", None)
        try:
            return {n: (override[id(p)] if override and id(p) in override
                        else p.data())
                    for n, p in self._reg_params.items()}
        except DeferredInitializationError:
            self.infer_shape(*args)
            for p in self._reg_params.values():
                if p._deferred_init is not None:
                    p._finish_deferred_init()
            return {n: p.data() for n, p in self._reg_params.items()}

    def forward(self, x, *args):
        from ..symbol.symbol import Symbol
        if isinstance(x, Symbol):
            # symbolic trace (gluon export / SymbolBlock composition):
            # parameters become graph variables by their full names
            from .. import symbol as sym_mod
            for p in self._reg_params.values():
                if p.shape is None or any(s == 0 for s in p.shape):
                    raise MXNetError(
                        f"{self.name}: cannot trace symbolically while "
                        f"parameter {p.name} has unresolved shape "
                        f"{p.shape}; run the block once on data first")
            params = {n: p.var() for n, p in self._reg_params.items()}
            return self.hybrid_forward(sym_mod, x, *args, **params)
        if self._active and not getattr(_in_trace, "value", False):
            return self._call_cached_op(x, *args)
        params = self._collect_param_values(x, *args)
        return self.hybrid_forward(nd_mod, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- CachedOp path -----------------------------------------------------
    def _ensure_initialized(self, *args):
        try:
            for p in self.collect_params().values():
                if p._data is None:
                    p.data()  # raises with a helpful message
            return True
        except DeferredInitializationError:
            return False

    def _call_cached_op(self, *args):
        if self._cached_plist is None:
            if not self._ensure_initialized(*args):
                # first call resolves deferred shapes imperatively (the
                # reference's deferred-init first pass); later calls compile
                prev = _in_trace_flag()
                _set_in_trace(True)
                try:
                    return self.forward(*args)
                finally:
                    _set_in_trace(prev)
            # parameter tree is static once shapes are resolved — walk once
            self._cached_plist = sorted(self.collect_params().items())
        plist = self._cached_plist
        pvals = [p.data()._data for _, p in plist]
        flat_in, in_spec = _flatten(list(args))
        in_datas = [a._data for a in flat_in]
        training = autograd.is_training()
        sig = (tuple((tuple(d.shape), str(d.dtype)) for d in in_datas),
               tuple((tuple(v.shape), str(v.dtype)) for v in pvals),
               training, in_spec if isinstance(in_spec, str) else str(in_spec))

        entry = self._cached_jit.get(sig)
        if entry is None:
            entry = self._build_cached(plist, in_spec, training)
            self._cached_jit[sig] = entry
        jfn, out_spec_box, aux_params_box = entry

        key = _random.next_key()

        def run(*datas):
            return jfn(tuple(datas[:len(pvals)]), key,
                       *datas[len(pvals):])

        raw = run(*pvals, *in_datas)
        flat_out_data, aux_data = raw
        outs = [NDArray(d) for d in flat_out_data]

        if autograd.is_recording():
            param_nds = [p.data() for _, p in plist]
            autograd._record_closure(
                f"cachedop_{self.name}",
                lambda *datas: jfn(tuple(datas[:len(pvals)]), key,
                                   *datas[len(pvals):])[0],
                param_nds + flat_in, outs)

        # write back functional aux updates (running stats)
        for p, d in zip(aux_params_box[0], aux_data):
            p._data._data = d

        flat = list(outs)
        return _regroup(flat, out_spec_box[0])

    def optimize_for(self, x, *args, backend="XLA"):
        """Partition the inference graph with a registered subgraph
        backend and keep using it from the hybridized call path (ref:
        gluon/block.py optimize_for; parity with CachedOp running the
        same graph passes as bind, src/imperative/cached_op.cc:685).

        With the default ``backend="XLA"`` conv+BN(+add)+relu chains
        collapse into ``_sg_xla_conv`` with the BN affine folded into
        the convolution weights (subgraph/xla_fuse.py). Training-mode
        calls (autograd.is_training()) bypass the partitioned graph —
        folding moving stats would silently freeze BN statistics."""
        out = self(x, *args)  # resolves deferred shapes imperatively
        self._optimized_backend = backend
        self._cached_jit = {}
        self._cached_plist = None
        self._active = True
        return out

    @staticmethod
    def _spec_nleaves(spec):
        if spec in ("0", "raw"):
            return 1
        return sum(HybridBlock._spec_nleaves(s) for s in spec[2])

    def _build_cached_partitioned(self, plist, in_spec, backend):
        """Symbolically trace, run the subgraph partitioner, and lower
        the optimized graph to a jitted fn with the same signature as
        `_build_cached`'s direct trace."""
        from ..symbol import Group
        from ..symbol import var as sym_var

        n_in = self._spec_nleaves(in_spec)
        placeholders = [sym_var(f"__cached_in{i}") for i in range(n_in)]
        flat = list(placeholders)
        args = _regroup(flat, in_spec)
        if not isinstance(args, list):
            args = [args]
        prev = _in_trace_flag()
        _set_in_trace(True)
        try:
            out = self.forward(*args)
        finally:
            _set_in_trace(prev)
        flat_out, out_spec = _flatten(out)
        sym = Group(list(flat_out)) if len(flat_out) > 1 else flat_out[0]
        opt = sym.get_backend_symbol(backend)
        needed = set(opt.list_inputs())

        def pure_fn(param_vals, key, *in_datas):
            bindings = {}
            for (n, _p), v in zip(plist, param_vals):
                if n in needed:
                    bindings[n] = NDArray(v)
            for i, d in enumerate(in_datas):
                bindings[f"__cached_in{i}"] = NDArray(d)
            prev_trace = _in_trace_flag()
            _set_in_trace(True)
            try:
                with _random.key_context(key):
                    res = opt.eval_dict(bindings)
            finally:
                _set_in_trace(prev_trace)
            res_list = res if isinstance(res, list) else [res]
            return [r._data for r in res_list], []

        return jax.jit(pure_fn), [out_spec], [[]]

    def _build_cached(self, plist, in_spec, training):
        """Trace the whole subtree once into a jitted pure function."""
        backend = getattr(self, "_optimized_backend", None)
        if backend and not training:
            try:
                return self._build_cached_partitioned(
                    plist, in_spec, backend)
            except Exception as e:  # noqa: BLE001 — un-traceable blocks
                import warnings
                warnings.warn(
                    f"optimize_for({backend!r}): symbolic partition "
                    f"failed ({e!r}); falling back to the direct trace")
        out_spec_box = [None]
        aux_params_box = [[]]
        params = [p for _, p in plist]

        def pure_fn(param_vals, key, *in_datas):
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(training)
            prev_trace = _in_trace_flag()
            _set_in_trace(True)
            override = {id(p): NDArray(v) for p, v in zip(params, param_vals)}
            old_map = getattr(_param_override, "map", None)
            _param_override.map = override
            if not hasattr(_aux_updates, "stack"):
                _aux_updates.stack = []
            _aux_updates.stack.append([])
            try:
                with _random.key_context(key):
                    flat_in = [NDArray(d) for d in in_datas]
                    args = _regroup(list(flat_in), in_spec)
                    if not isinstance(args, list):
                        args = [args]
                    out = self.forward(*args)
                aux = _aux_updates.stack[-1]
            finally:
                _aux_updates.stack.pop()
                _param_override.map = old_map
                _set_in_trace(prev_trace)
                autograd.set_training(prev_train)
                autograd.set_recording(prev_rec)
            flat_out, out_spec = _flatten(out)
            out_spec_box[0] = out_spec
            aux_params_box[0] = [p for p, _ in aux]
            return ([o._data for o in flat_out],
                    [v._data for _, v in aux])

        return jax.jit(pure_fn), out_spec_box, aux_params_box

    def export(self, path, epoch=0):
        """Export to symbol JSON + params (ref: block.py export).

        Requires the network to have run at least once so shapes are known.
        Traces hybrid_forward with Symbol placeholders.
        """
        from .. import symbol as sym_mod
        from ..symbol.trace import trace_block
        out, params = trace_block(self)
        out.save(f"{path}-symbol.json")
        aux_names = set(out.list_auxiliary_states())
        payload = {}
        for name, p in params.items():
            prefix = "aux" if name in aux_names else "arg"
            payload[f"{prefix}:{name}"] = p.data()
        nd_mod.save(f"{path}-{epoch:04d}.params", payload)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"


_in_trace = threading.local()
_param_override = threading.local()


def _in_trace_flag():
    return getattr(_in_trace, "value", False)


def _set_in_trace(v):
    _in_trace.value = v


def infer_shapes(block, *input_shapes, dtype=None):
    """Resolve a block's deferred parameter shapes with ONE abstract
    forward pass — no op is compiled or executed on the device.

    ``jax.eval_shape`` runs the eager path on shape tracers, so each
    layer's shape inference fires and deferred initializers materialize
    real (concrete — see ndarray._materialize) parameter arrays. This is
    the shared warm-up used by bench.py, __graft_entry__.entry() and
    contrib.quantization.quantize_net; the reference's analogue is the
    deferred-init first pass of HybridBlock (gluon/block.py:860
    infer_shape)."""
    import jax.numpy as jnp
    dtype = dtype or jnp.float32

    def _warm(*datas):
        prev = _in_trace_flag()
        _set_in_trace(True)
        try:
            out = block.forward(*[NDArray(d) for d in datas])
            flat, _spec = _flatten(out)
            return [o._data for o in flat]
        finally:
            _set_in_trace(prev)

    jax.eval_shape(_warm, *[jax.ShapeDtypeStruct(tuple(s), dtype)
                            for s in input_shapes])


class SymbolBlock(HybridBlock):
    """Construct a block from a Symbol (ref: gluon/block.py:952)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..symbol.symbol import Symbol
        self._out_sym = outputs if isinstance(outputs, Symbol) else outputs[0]
        input_names = {s.name for s in self._inputs}
        for name in self._out_sym.list_inputs():
            if name not in input_names:
                self._reg_params[name] = self.params.get(
                    name, allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        out = sym_mod.load(symbol_file)
        inputs = [sym_mod.var(n) for n in (
            input_names if isinstance(input_names, (list, tuple))
            else [input_names])]
        blk = SymbolBlock(out, inputs)
        if param_file:
            loaded = nd_mod.load(param_file)
            cleaned = {}
            for k, v in loaded.items():
                k = k.split(":", 1)[-1]
                cleaned[k] = v
            for name, p in blk._reg_params.items():
                if name in cleaned:
                    p.set_data(cleaned[name])
            if ctx:
                blk.collect_params().reset_ctx(ctx)
        return blk

    def forward(self, *args):
        bindings = {s.name: a for s, a in zip(self._inputs, args)}
        for name, p in self._reg_params.items():
            if p._data is not None:
                bindings[name] = p.data()
        return self._out_sym.eval_dict(bindings)

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError
