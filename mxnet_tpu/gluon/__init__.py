"""Gluon — imperative/hybrid layer API (ref: python/mxnet/gluon/)."""
from . import parameter
from .parameter import Parameter, ParameterDict, Constant
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from .trainer import Trainer
from . import rnn
from . import data
from . import model_zoo
from . import contrib
from .utils import split_and_load, split_data, clip_global_norm
