"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

This environment has no network egress: datasets read local files when
present (standard idx/bin formats) and otherwise raise with instructions —
tests use synthetic ArrayDatasets instead.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ....base import MXNetError
from ....ndarray import array
from ..dataset import ArrayDataset, Dataset




class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx files (train-images-idx3-ubyte(.gz) etc.)."""

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._base = ("train" if train else "t10k")
        super().__init__(root, train, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        magic = struct.unpack(">I", data[:4])[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
        arr = np.frombuffer(data, np.uint8, offset=4 + 4 * ndim)
        return arr.reshape(dims)

    def _find(self, name):
        for cand in (name, name + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise MXNetError(
            f"MNIST file {name} not found under {self._root}; this "
            "environment has no network egress — place the idx files there "
            "or use a synthetic ArrayDataset")

    def _get_data(self):
        images = self._read_idx(self._find(f"{self._base}-images-idx3-ubyte"))
        labels = self._read_idx(self._find(f"{self._base}-labels-idx1-ubyte"))
        self._data = array(images.reshape(-1, 28, 28, 1).astype(np.float32))
        self._label = labels.astype(np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] if self._train \
            else ["test_batch.bin"]
        datas, labels = [], []
        for fname in files:
            path = os.path.join(self._root, fname)
            if not os.path.exists(path):
                raise MXNetError(
                    f"CIFAR10 file {fname} not found under {self._root} "
                    "(no network egress in this environment)")
            raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
            labels.append(raw[:, 0])
            datas.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                         .transpose(0, 2, 3, 1))
        self._data = array(np.concatenate(datas).astype(np.float32))
        self._label = np.concatenate(labels).astype(np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=False, transform=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        fname = "train.bin" if self._train else "test.bin"
        path = os.path.join(self._root, fname)
        if not os.path.exists(path):
            raise MXNetError(f"CIFAR100 file {fname} not found under "
                             f"{self._root}")
        raw = np.fromfile(path, np.uint8).reshape(-1, 3074)
        self._label = raw[:, 1 if self._fine_label else 0].astype(np.int32)
        self._data = array(raw[:, 2:].reshape(-1, 3, 32, 32)
                           .transpose(0, 2, 3, 1).astype(np.float32))


class ImageRecordDataset(Dataset):
    """Images + labels packed in a RecordIO file (ref: datasets.py)."""

    def __init__(self, filename, flag=1, transform=None):
        import threading

        from ....recordio import MXIndexedRecordIO, unpack_img
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")
        self._flag = flag
        self._transform = transform
        self._unpack = unpack_img
        # read_idx is seek+read on one shared handle; DataLoader thread
        # workers hit it concurrently (decode stays parallel, only the
        # file read serializes)
        self._read_lock = threading.Lock()

    def __len__(self):
        return len(self._record.keys)

    def raw_payload(self, idx):
        """(undecoded payload bytes, label) — the seam the DataLoader's
        native batch path reads so decode+augment can run in the C++
        pool instead of per-item Python (ref: the reference feeds raw
        records straight to its OMP decoder, iter_image_recordio_2.cc)."""
        from ....recordio import unpack
        with self._read_lock:
            record = self._record.read_idx(self._record.keys[idx])
        header, payload = unpack(record)
        return payload, header.label

    def __getitem__(self, idx):
        with self._read_lock:
            record = self._record.read_idx(self._record.keys[idx])
        from ....recordio import cv2_present, decode_payload, unpack
        from ...._native import decode_jpeg
        header, payload = unpack(record)
        img = decode_jpeg(payload) if self._flag != 0 else None
        if img is None:
            # cv2/PIL fallback on the already-extracted payload; items
            # must come out decoder-independent: color requests always
            # (H, W, 3) RGB
            img = decode_payload(payload, iscolor=self._flag)
            if self._flag != 0:
                if img.ndim == 2:
                    img = img[:, :, None].repeat(3, axis=2)
                elif img.shape[2] == 3 and cv2_present() \
                        and payload[:6] != b"\x93NUMPY":
                    # cv2 decodes BGR; normalize to RGB (4-channel
                    # BGRA etc. is passed through untouched)
                    img = np.ascontiguousarray(img[:, :, ::-1])
        if self._transform is not None:
            return self._transform(array(img), header.label)
        return array(img), header.label
