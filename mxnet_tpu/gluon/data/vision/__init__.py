from .datasets import MNIST, FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset
from . import transforms
