"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Image ops run through the image op family (src/operator/image/ analogue):
HWC uint8/float inputs, ToTensor converts to CHW float32/255.
"""
from __future__ import annotations

import numpy as np

from ....ndarray import NDArray, array
from ...block import Block, HybridBlock
from ...nn import HybridSequential


class Compose(HybridSequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC [0,255] -> CHW [0,1] float32."""

    def hybrid_forward(self, F, x):
        x = F.Cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = np.asarray(self._mean, np.float32).reshape(-1, 1, 1)
        std = np.asarray(self._std, np.float32).reshape(-1, 1, 1)
        return (x - array(mean)) / array(std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        data = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        h, w = self._size[1], self._size[0]
        if data.ndim == 3:
            out = jax.image.resize(data.astype(jnp.float32),
                                   (h, w, data.shape[2]), "linear")
        else:
            out = jax.image.resize(data.astype(jnp.float32),
                                   (data.shape[0], h, w, data.shape[3]),
                                   "linear")
        return NDArray(out)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0 = max((H - h) // 2, 0)
        x0 = max((W - w) // 2, 0)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            aspect = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target_area * aspect)))
            h = int(round(np.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                return Resize(self._size)(crop)
        return Resize(self._size)(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            from .... import ndarray as F
            return F.flip(x, axis=x.ndim - 2)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            from .... import ndarray as F
            return F.flip(x, axis=x.ndim - 3)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._b, self._b)
        return x * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._c, self._c)
        gray = x.mean()
        return x * alpha + gray * (1 - alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._s, self._s)
        coef = array(np.array([0.299, 0.587, 0.114], np.float32))
        gray = (x * coef).sum(axis=-1, keepdims=True)
        return x * alpha + gray * (1 - alpha)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        order = np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x
