"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference feeds batches through multiprocessing workers + POSIX-shm fd
rebuilding. Forking a process that holds a PJRT/TPU client is unsafe, so the
TPU-native loader uses a thread pool: decode/augment run in Python threads
(NumPy/opencv release the GIL), batches materialize as pinned host arrays and
device transfer overlaps compute via the async stream — the same
PrefetcherIter pattern as src/io/iter_prefetcher.h:47.

When the dataset is an ImageRecordDataset and the transform pipeline is
the standard vision shape (flip? + center-crop + ToTensor + Normalize?),
whole batches bypass Python entirely: raw JPEG payloads go to the
_native/imgdec.cc libjpeg thread pool, which decodes, crops, mirrors and
normalizes straight into a pooled NCHW float32 buffer — the same one
OMP pipeline that serves io.ImageRecordIter (ref:
src/io/iter_image_recordio_2.cc:364-445 serves both of the reference's
paths). Unsupported pipelines fall back to the per-item Python path.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def compile_native_plan(fn):
    """Map a transforms.Compose onto imgdec.cc's kernel if its steps are
    exactly [RandomFlipLeftRight?] [CenterCrop] [ToTensor] [Normalize?].
    Returns {"th","tw","flip","mean","std"} or None. The kernel works on
    raw 0..255 pixels, so ToTensor's /255 and Normalize fold into the
    affine: ((px/255) - m) / s == (px - 255m) / (255s)."""
    from .vision import transforms as T

    if not isinstance(fn, T.Compose):
        return None
    steps = list(fn._children.values())
    flip = False
    crop = None
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)
    i = 0
    if i < len(steps) and isinstance(steps[i], T.RandomFlipLeftRight):
        flip = True
        i += 1
    if i < len(steps) and isinstance(steps[i], T.CenterCrop):
        crop = steps[i]._size  # (w, h)
        i += 1
    else:
        return None  # no fixed output size -> variable shapes, bail
    if not (i < len(steps) and isinstance(steps[i], T.ToTensor)):
        return None
    i += 1
    if i < len(steps) and isinstance(steps[i], T.Normalize):
        mean = np.broadcast_to(np.asarray(steps[i]._mean, np.float32),
                               (3,)).copy()
        std = np.broadcast_to(np.asarray(steps[i]._std, np.float32),
                              (3,)).copy()
        i += 1
    if i != len(steps):
        return None  # unrecognized trailing transforms
    w, h = crop
    return {"th": int(h), "tw": int(w), "flip": flip,
            "mean": mean * 255.0, "std": std * 255.0}


def default_batchify_fn(data):
    if isinstance(data[0], NDArray):
        from ... import ndarray as F
        return F.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = np.asarray(data)
    return array(arr)


class DataLoader:
    """``num_workers`` with the default ``thread_pool=True`` keeps the
    in-process executor above; ``thread_pool=False`` routes
    native-mappable datasets to the multi-process sharded decode
    pipeline (io/pipeline.py) — worker PROCESSES with private libjpeg
    pools feeding a shared-memory ring, the production path for
    many-core hosts where the GIL caps the thread loader.

    ``prefetch_to_device=True`` double-buffers device transfer: a
    feeder thread ``jax.device_put``s batch k+1 while step k runs
    (defaults to the ``MXTPU_IO_PREFETCH_DEVICE`` knob).
    ``pin_memory=True`` routes to the same feeder — on TPU hosts the
    honest meaning of "pin" is staging the batch onto the device ahead
    of the step; it was previously accepted and silently ignored."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, prefetch_to_device=None, sharding=None):
        self._dataset = dataset
        custom_order = sampler is not None or batch_sampler is not None
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle conflicts with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._native = None
        if batchify_fn is None:
            self._native = self._compile_native(dataset)
        from ...base import get_env
        if pin_memory and prefetch_to_device is None:
            import warnings
            warnings.warn(
                "DataLoader(pin_memory=True) routes to the device "
                "feeder on this backend (prefetch_to_device): batches "
                "are staged onto the device ahead of the step instead "
                "of into pinned host pages", stacklevel=2)
            prefetch_to_device = True
        if prefetch_to_device is None:
            prefetch_to_device = get_env("MXTPU_IO_PREFETCH_DEVICE",
                                         False, bool)
        self._prefetch_device = bool(prefetch_to_device)
        self._sharding = sharding
        self._mp_pipeline = None
        self._mp_config = None
        if not thread_pool and self._num_workers > 0 and not custom_order:
            self._mp_config = self._compile_multiprocess(
                dataset, batch_size, shuffle)

    def _compile_native(self, dataset):
        """(source dataset, plan) when the dataset chain is
        ImageRecordDataset -> transform_first(<native-mappable Compose>);
        None otherwise."""
        from .dataset import _LazyTransformDataset
        from .vision.datasets import ImageRecordDataset

        if not isinstance(dataset, _LazyTransformDataset):
            return None
        fn = getattr(dataset._fn, "_transform_first", None)
        src = dataset._data
        if fn is None or not isinstance(src, ImageRecordDataset):
            return None
        if src._flag == 0 or src._transform is not None:
            return None
        plan = compile_native_plan(fn)
        if plan is None:
            return None
        return src, plan

    def _compile_multiprocess(self, dataset, batch_size, shuffle):
        """Pipeline construction kwargs when the dataset shape maps
        onto the sharded decode pipeline exactly; None falls back to
        the thread executor. Requirements: a native-mappable
        ImageRecordDataset chain (same check as the C++ batch path), the
        auto-built sequential/random sampler (a custom sampler owns its
        own order — the pipeline shards its own), and a record count
        divisible by workers*batch so every record is delivered exactly
        once per epoch (the pipeline's discard-tail semantics would
        otherwise diverge from last_batch="keep")."""
        if self._native is None or batch_size is None:
            return None
        src, plan = self._native
        n = len(dataset)
        if n % (self._num_workers * batch_size) != 0:
            return None
        # seed DERIVED from (not drawn from) the global RNG state:
        # deterministic under np.random.seed like RandomSampler, but
        # constructing the loader consumes no draws — an mp loader and
        # a thread loader leave the user's RNG stream identical
        seed = int(np.random.get_state()[1][0]) & 0x7FFFFFFF
        return {
            "path_imgrec": src._record.uri,
            "data_shape": (3, plan["th"], plan["tw"]),
            "batch_size": int(batch_size),
            "num_workers": self._num_workers,
            "shuffle": bool(shuffle),
            "rand_mirror": bool(plan["flip"]),
            "mean": plan["mean"], "std": plan["std"],
            "seed": seed,
        }

    def close(self):
        """Tear down the worker processes + shared memory (also runs
        from __del__; iterating again respawns them)."""
        if self._mp_pipeline is not None:
            self._mp_pipeline.close()
            self._mp_pipeline = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        if self._native is not None:
            batch = self._load_batch_native(indices)
            if batch is not None:
                return batch
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _load_batch_native(self, indices):
        """Whole-batch decode+augment in the C++ pool; None falls back
        to the Python path (lib absent, a non-JPEG record, an image the
        kernel refuses e.g. smaller than the crop)."""
        from ... import _native
        from ...base import MXNetError

        src, plan = self._native
        payloads, labels = [], []
        for i in indices:
            payload, label = src.raw_payload(i)
            if payload[:2] != b"\xff\xd8":
                return None
            payloads.append(payload)
            labels.append(np.atleast_1d(
                np.asarray(label, np.float32)))
        n = len(payloads)
        uv = np.full((n, 2), -1.0, np.float32)  # center crop
        mirror = ((np.random.rand(n) < 0.5) if plan["flip"]
                  else np.zeros(n)).astype(np.uint8)
        try:
            # with executor workers in flight, each call decodes its
            # batch single-threaded — the parallelism is across batches
            # (N workers x N-thread pools would oversubscribe the host)
            out = _native.decode_batch(
                payloads, plan["th"], plan["tw"], uv, mirror,
                plan["mean"], plan["std"],
                nthreads=1 if self._num_workers else None)
        except MXNetError:
            # e.g. an image smaller than the crop: the Python
            # CenterCrop clamps instead — let that path decide
            return None
        if out is None:
            return None
        lab = np.stack(labels)
        if lab.shape[1] == 1:  # scalar labels batch as (n,) like the
            lab = lab[:, 0]    # per-item path
        return [array(out), array(lab)]

    def _iter_multiprocess(self):
        """One epoch off the sharded pipeline: [data, label] batches,
        worker processes kept alive across epochs."""
        from ...io.pipeline import ShardedRecordPipeline
        if self._mp_pipeline is None:
            self._mp_pipeline = ShardedRecordPipeline(**self._mp_config)
        else:
            self._mp_pipeline.reset()
        for batch in self._mp_pipeline:
            yield [batch.data[0], batch.label[0]]

    def _iter_batches(self):
        if self._mp_config is not None:
            yield from self._iter_multiprocess()
            return
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch or self._num_workers):
                    futures.append(pool.submit(self._load_batch, next(it)))
            except StopIteration:
                pass
            while futures:
                batch = futures.pop(0).result()
                try:
                    futures.append(pool.submit(self._load_batch, next(it)))
                except StopIteration:
                    pass
                yield batch

    def __iter__(self):
        if not self._prefetch_device:
            yield from self._iter_batches()
            return
        # double-buffered device prefetch: the feeder thread device_puts
        # batch k+1 while the training step consumes batch k; the
        # residual queue wait is charged to the step breakdown's
        # data_time (io/pipeline.py DeviceFeeder)
        from ...io.pipeline import DeviceFeeder
        feeder = DeviceFeeder(self._iter_batches(),
                              sharding=self._sharding)
        try:
            while True:
                try:
                    yield feeder.get()
                except StopIteration:
                    return
        finally:
            feeder.close()
