"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference feeds batches through multiprocessing workers + POSIX-shm fd
rebuilding. Forking a process that holds a PJRT/TPU client is unsafe, so the
TPU-native loader uses a thread pool: decode/augment run in Python threads
(NumPy/opencv release the GIL), batches materialize as pinned host arrays and
device transfer overlaps compute via the async stream — the same
PrefetcherIter pattern as src/io/iter_prefetcher.h:47.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    if isinstance(data[0], NDArray):
        from ... import ndarray as F
        return F.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = np.asarray(data)
    return array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle conflicts with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch or self._num_workers):
                    futures.append(pool.submit(self._load_batch, next(it)))
            except StopIteration:
                pass
            while futures:
                batch = futures.pop(0).result()
                try:
                    futures.append(pool.submit(self._load_batch, next(it)))
                except StopIteration:
                    pass
                yield batch
