"""Datasets (ref: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from ...ndarray import NDArray, array


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([s for s in self if fn(s)])

    def take(self, count):
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn) if lazy else \
            SimpleDataset([fn(*s) if isinstance(s, tuple) else fn(s)
                           for s in self])

    def transform_first(self, fn, lazy=True):
        def first(x, *args):
            return (fn(x),) + args if args else fn(x)
        # the DataLoader's native batch path unwraps this to see whether
        # the user pipeline compiles onto the C++ decode kernel
        first._transform_first = fn
        return self.transform(first, lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class ArrayDataset(Dataset):
    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            assert len(a) == self._length, "all arrays must be same length"
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over an indexed RecordIO file (ref: data/dataset.py)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
