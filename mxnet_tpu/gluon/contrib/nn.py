"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ..block import HybridBlock
from ..nn import SyncBatchNorm  # noqa: F401  (re-export: lives in core nn here)


class Concurrent(HybridBlock):
    """Run children on the same input and concat outputs."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.Concat(*out, dim=self.axis)


class HybridConcurrent(Concurrent):
    pass


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x
