"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ..block import HybridBlock
from ..nn import Embedding as _Embedding
from ..nn import SyncBatchNorm  # noqa: F401  (re-export: lives in core nn here)


class Concurrent(HybridBlock):
    """Run children on the same input and concat outputs."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.Concat(*out, dim=self.axis)


class HybridConcurrent(Concurrent):
    pass


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(_Embedding):
    """Embedding whose gradient is row-sparse (ref:
    contrib/nn/basic_layers.py:116 SparseEmbedding) — a thin veneer
    over nn.Embedding(sparse_grad=True): the row-granular optimizer
    kernels touch only the rows a batch used, and dist kvstores pull
    rows on demand."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, prefix=None, params=None):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, prefix=prefix, params=params)

    def hybrid_forward(self, F, x, weight):
        return F._contrib_SparseEmbedding(
            x, weight, input_dim=self._input_dim,
            output_dim=self._output_dim)

    def __repr__(self):
        return (f"SparseEmbedding({self._input_dim} -> "
                f"{self._output_dim})")
