"""Contrib RNN cells (ref: python/mxnet/gluon/contrib/rnn/).

Conv-RNN variants; VariationalDropoutCell."""
from __future__ import annotations

from ..rnn.rnn_cell import ModifierCell


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask across timesteps (ref: contrib/rnn/rnn_cell.py)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._mask_in = None
        self._mask_out = None

    def reset(self):
        self._mask_in = None
        self._mask_out = None

    def __call__(self, x, states):
        from ... import autograd, ndarray as F
        if autograd.is_training():
            if self.drop_inputs:
                if self._mask_in is None or self._mask_in.shape != x.shape:
                    self._mask_in = F.Dropout(F.ones_like(x),
                                              p=self.drop_inputs,
                                              mode="always")
                x = x * self._mask_in
        out, states = self.base_cell(x, states)
        if autograd.is_training() and self.drop_outputs:
            if self._mask_out is None or self._mask_out.shape != out.shape:
                self._mask_out = F.Dropout(F.ones_like(out),
                                           p=self.drop_outputs, mode="always")
            out = out * self._mask_out
        return out, states

    forward = __call__
