"""Contrib RNN cells (ref: python/mxnet/gluon/contrib/rnn/).

Conv-RNN variants; VariationalDropoutCell."""
from __future__ import annotations

from ..rnn.rnn_cell import ModifierCell, RecurrentCell


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask across timesteps (ref: contrib/rnn/rnn_cell.py)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._mask_in = None
        self._mask_out = None

    def reset(self):
        self._mask_in = None
        self._mask_out = None

    def __call__(self, x, states):
        from ... import autograd, ndarray as F
        if autograd.is_training():
            if self.drop_inputs:
                if self._mask_in is None or self._mask_in.shape != x.shape:
                    self._mask_in = F.Dropout(F.ones_like(x),
                                              p=self.drop_inputs,
                                              mode="always")
                x = x * self._mask_in
        out, states = self.base_cell(x, states)
        if autograd.is_training() and self.drop_outputs:
            if self._mask_out is None or self._mask_out.shape != out.shape:
                self._mask_out = F.Dropout(F.ones_like(out),
                                           p=self.drop_outputs, mode="always")
            out = out * self._mask_out
        return out, states

    forward = __call__


# ---------------------------------------------------------------------------
# Convolutional RNN cells (ref: python/mxnet/gluon/contrib/rnn/
# conv_rnn_cell.py — i2h/h2h become convolutions over spatial state)
# ---------------------------------------------------------------------------


from ..nn.conv_layers import _pair as _tuple


def _conv_out(dims, kernels, pads, dilates):
    return tuple((d + 2 * p - dl * (k - 1) - 1) + 1
                 for d, k, p, dl in zip(dims, kernels, pads, dilates))


class _ConvRNNCellBase(RecurrentCell):
    """Shared conv-cell machinery: i2h conv over the input, h2h conv
    over the spatial hidden state (kernel pads chosen so the state
    shape is invariant)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 dims=2, activation="tanh", num_gates=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._ndims = dims
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._hidden = int(hidden_channels)
        self._activation = activation
        self._gates = num_gates
        self._i2h_kernel = _tuple(i2h_kernel, dims)
        self._h2h_kernel = _tuple(h2h_kernel, dims)
        self._i2h_pad = _tuple(i2h_pad, dims)
        self._i2h_dilate = _tuple(i2h_dilate, dims)
        self._h2h_dilate = _tuple(h2h_dilate, dims)
        for name, t in (("i2h_kernel", self._i2h_kernel),
                        ("h2h_kernel", self._h2h_kernel),
                        ("i2h_pad", self._i2h_pad),
                        ("i2h_dilate", self._i2h_dilate),
                        ("h2h_dilate", self._h2h_dilate)):
            if len(t) != dims:
                raise ValueError(
                    f"{name} {t} must have {dims} dims for this cell")
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    "h2h_kernel dims must be odd so the state shape is "
                    f"invariant; got {self._h2h_kernel}")
        self._h2h_pad = tuple(d * (k - 1) // 2 for k, d in
                              zip(self._h2h_kernel, self._h2h_dilate))
        self._state_spatial = _conv_out(
            self._input_shape[1:], self._i2h_kernel, self._i2h_pad,
            self._i2h_dilate)
        if any(d <= 0 for d in self._state_spatial):
            raise ValueError(
                f"i2h kernel {self._i2h_kernel} / pad {self._i2h_pad} "
                f"leave no spatial state for input {self._input_shape}: "
                f"computed state spatial {self._state_spatial}")
        ngh = self._gates * self._hidden
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(ngh, self._input_shape[0]) + self._i2h_kernel,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(ngh, self._hidden) + self._h2h_kernel,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ngh,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ngh,), init=h2h_bias_initializer)

    def _convs(self, F, x, h, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias):
        i2h = F.Convolution(
            x, i2h_weight, i2h_bias, kernel=self._i2h_kernel,
            pad=self._i2h_pad, dilate=self._i2h_dilate,
            num_filter=self._gates * self._hidden)
        h2h = F.Convolution(
            h, h2h_weight, h2h_bias, kernel=self._h2h_kernel,
            pad=self._h2h_pad, dilate=self._h2h_dilate,
            num_filter=self._gates * self._hidden)
        return i2h, h2h

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden) + self._state_spatial
        return [{"shape": shape} for _ in range(self._num_states)]


class _ConvRNNCell(_ConvRNNCellBase):
    _num_states = 1

    def __init__(self, *args, **kwargs):
        super().__init__(*args, num_gates=1, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, x, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class _ConvLSTMCell(_ConvRNNCellBase):
    _num_states = 2

    def __init__(self, *args, **kwargs):
        super().__init__(*args, num_gates=4, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, x, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(sl[0], act_type="sigmoid")
        f = F.Activation(sl[1], act_type="sigmoid")
        g = F.Activation(sl[2], act_type=self._activation)
        o = F.Activation(sl[3], act_type="sigmoid")
        c = f * states[1] + i * g
        h = o * F.Activation(c, act_type=self._activation)
        return h, [h, c]


class _ConvGRUCell(_ConvRNNCellBase):
    _num_states = 1

    def __init__(self, *args, **kwargs):
        super().__init__(*args, num_gates=3, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, x, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        i_r, i_z, i_n = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h_r, h_z, h_n = F.SliceChannel(h2h, num_outputs=3, axis=1)
        r = F.Activation(i_r + h_r, act_type="sigmoid")
        z = F.Activation(i_z + h_z, act_type="sigmoid")
        n = F.Activation(i_n + r * h_n, act_type=self._activation)
        out = (1 - z) * n + z * states[0]
        return out, [out]


def _make_cell(base, dims, name):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     activation="tanh", i2h_weight_initializer=None,
                     h2h_weight_initializer=None,
                     i2h_bias_initializer="zeros",
                     h2h_bias_initializer="zeros", prefix=None,
                     params=None):
            super().__init__(
                input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                i2h_pad=i2h_pad, i2h_dilate=i2h_dilate,
                h2h_dilate=h2h_dilate, dims=dims,
                activation=activation,
                i2h_weight_initializer=i2h_weight_initializer,
                h2h_weight_initializer=h2h_weight_initializer,
                i2h_bias_initializer=i2h_bias_initializer,
                h2h_bias_initializer=h2h_bias_initializer,
                prefix=prefix, params=params)

    Cell.__name__ = Cell.__qualname__ = name
    Cell.__doc__ = (f"{name} (ref: gluon/contrib/rnn/conv_rnn_cell.py "
                    f"{name}) — recurrent cell whose i2h/h2h transforms "
                    "are convolutions over spatial state.")
    return Cell


Conv1DRNNCell = _make_cell(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make_cell(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make_cell(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make_cell(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make_cell(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make_cell(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make_cell(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make_cell(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make_cell(_ConvGRUCell, 3, "Conv3DGRUCell")
