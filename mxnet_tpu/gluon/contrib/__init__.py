"""Gluon contrib (ref: python/mxnet/gluon/contrib/)."""
from . import nn
from . import rnn
from . import data
