"""Contrib data utilities (ref: python/mxnet/gluon/contrib/data/).

No network egress in this environment: the WikiText datasets read
pre-downloaded token files from ``root`` (same convention as the core
vision datasets) and raise with instructions otherwise.
"""
from __future__ import annotations

import os

import numpy as np

from ...base import MXNetError
from ..data.sampler import Sampler
from ..data.dataset import Dataset


class IntervalSampler(Sampler):
    """Samples [0, length) at fixed intervals (ref:
    contrib/data/sampler.py:25 — used to deal sequence shards across
    truncated-BPTT streams)."""

    def __init__(self, length, interval, rollover=True):
        if not 1 <= interval <= length:
            raise MXNetError(
                f"interval {interval} must be in [1, length={length}]")
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        return self._length if self._rollover else \
            len(range(0, self._length, self._interval))


class _WikiText(Dataset):
    """Language-modelling dataset over a local tokens file: the text is
    split on whitespace, vocab built on first use, and samples are
    fixed-length id sequences (ref: contrib/data/text.py _WikiText —
    the download step is out of scope here; point ``root`` at the
    extracted .tokens files)."""

    _fname = None

    def __init__(self, root, segment="train", seq_len=35, vocab=None):
        root = os.path.expanduser(root)
        path = os.path.join(root, self._fname.format(segment=segment))
        if not os.path.exists(path):
            raise MXNetError(
                f"{type(self).__name__}: tokens file {path} not found; "
                "download the dataset out of band and point root= at it")
        with open(path, encoding="utf-8") as f:
            tokens = f.read().replace("\n", " <eos> ").split()
        if vocab is None:
            from ...contrib.text.vocab import Vocabulary
            from collections import Counter
            vocab = Vocabulary(Counter(tokens))
        self.vocab = vocab
        idx = vocab.to_indices(tokens)
        n = (len(idx) - 1) // seq_len
        self._seq_len = seq_len
        self._data = np.asarray(idx[:n * seq_len], np.int32) \
            .reshape(n, seq_len)
        self._label = np.asarray(idx[1:n * seq_len + 1], np.int32) \
            .reshape(n, seq_len)

    def __len__(self):
        return len(self._data)

    def __getitem__(self, i):
        from ...ndarray import array
        return array(self._data[i]), array(self._label[i])


class WikiText2(_WikiText):
    _fname = "wiki.{segment}.tokens"


class WikiText103(_WikiText):
    _fname = "wiki.{segment}.tokens"
