"""Fused RNN layers (ref: python/mxnet/gluon/rnn/rnn_layer.py).

Backed by the fused RNN op (ops/rnn.py — lax.scan over time, MXU matmuls
hoisted out of the loop), mirroring how the reference layers wrap the
cudnn/CPU fused kernel (src/operator/rnn-inl.h).
"""
from __future__ import annotations

from ...base import MXNetError
from ...ops.rnn import rnn_param_size, _NGATES
from .. import parameter
from ..block import HybridBlock


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout!r}")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        ng = _NGATES[mode]
        with self.name_scope():
            # per-(layer,direction) i2h/h2h weights+biases; flattened into the
            # fused op's parameter vector at forward (same layout contract)
            self._unfused_names = []
            for layer in range(num_layers):
                isz = input_size if layer == 0 else hidden_size * self._dir
                for d in range(self._dir):
                    sfx = ["l", "r"][d] + str(layer)
                    setattr(self, f"{sfx}_i2h_weight", self.params.get(
                        f"{sfx}_i2h_weight", shape=(ng * hidden_size, isz),
                        init=i2h_weight_initializer, allow_deferred_init=True))
                    setattr(self, f"{sfx}_h2h_weight", self.params.get(
                        f"{sfx}_h2h_weight",
                        shape=(ng * hidden_size, hidden_size),
                        init=h2h_weight_initializer, allow_deferred_init=True))
                    setattr(self, f"{sfx}_i2h_bias", self.params.get(
                        f"{sfx}_i2h_bias", shape=(ng * hidden_size,),
                        init=i2h_bias_initializer, allow_deferred_init=True))
                    setattr(self, f"{sfx}_h2h_bias", self.params.get(
                        f"{sfx}_h2h_bias", shape=(ng * hidden_size,),
                        init=h2h_bias_initializer, allow_deferred_init=True))
                    self._unfused_names.append(sfx)

    def infer_shape(self, x, *args):
        isz = x.shape[2] if self._layout == "TNC" else x.shape[-1]
        ng = _NGATES[self._mode]
        H = self._hidden_size
        for layer in range(self._num_layers):
            in_sz = isz if layer == 0 else H * self._dir
            for d in range(self._dir):
                sfx = ["l", "r"][d] + str(layer)
                self._reg_params[f"{sfx}_i2h_weight"].shape_inferred(
                    (ng * H, in_sz))

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        func = func or F.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], **kwargs))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch_size = inputs.shape[1]
        explicit_states = states is not None
        if states is None:
            states = self.begin_state(batch_size)
        if not isinstance(states, (list, tuple)):
            states = [states]
        # flatten params in the fused op's layout: all weights, then biases
        flat = []
        for sfx in self._unfused_names:
            flat.append(params[f"{sfx}_i2h_weight"].reshape(-1))
            flat.append(params[f"{sfx}_h2h_weight"].reshape(-1))
        for sfx in self._unfused_names:
            flat.append(params[f"{sfx}_i2h_bias"])
            flat.append(params[f"{sfx}_h2h_bias"])
        pvec = F.concat(*flat, dim=0)
        args = [inputs, pvec, states[0]]
        if self._mode == "lstm":
            args.append(states[1])
        out = F.RNN(*args, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        outputs, *out_states = out if isinstance(out, tuple) else (out,)
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if explicit_states:
            return outputs, list(out_states)
        return outputs

    def __call__(self, inputs, states=None):
        return super().__call__(inputs, states) if states is not None \
            else super().__call__(inputs)


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, mode, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
