"""Recurrent cells for step-wise / unrolled execution
(ref: python/mxnet/gluon/rnn/rnn_cell.py). Gate orders match the fused op
(LSTM: i,f,g,o; GRU: r,z,n) so cell and fused-layer parameters interconvert.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def reset(self):
        """Reset before re-use, e.g. at the start of each unroll
        (ref: rnn_cell.py BaseRNNCell.reset). Clears per-sequence state in
        modifier cells (zoneout prev-output, variational dropout masks)."""
        for child in self._children.values():
            if isinstance(child, RecurrentCell):
                child.reset()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        func = func or F.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        for t in range(length):
            if axis == 0:
                x = inputs[t]
            else:
                x = F.squeeze(F.slice_axis(inputs, axis=axis, begin=t,
                                           end=t + 1), axis=axis)
            out, new_states = self(x, states)
            if valid_length is not None:
                # freeze each sequence's state at its last valid step
                # (SequenceLast semantics, ref rnn_cell.py:?unroll)
                still = valid_length > t  # (B,)
                states = [F.where(F.reshape(still,
                                            shape=(-1,) + (1,) * (new.ndim - 1)),
                                  new, old)
                          for new, old in zip(new_states, states)]
            else:
                states = new_states
            outputs.append(out)
        if not merge_outputs and merge_outputs is not None \
                and valid_length is None:
            return outputs, states
        merged = F.stack(*outputs, axis=axis)
        if valid_length is not None:
            merged = F.SequenceMask(merged, valid_length,
                                    use_sequence_length=True, axis=axis)
        if merge_outputs or merge_outputs is None:
            return merged, states
        outputs = list(F.split(merged, num_outputs=length, axis=axis,
                               squeeze_axis=True)) if length > 1 else \
            [F.squeeze(merged, axis=axis)]
        return outputs, states

    def hybrid_forward(self, F, x, states, **params):
        raise NotImplementedError


HybridRecurrentCell = RecurrentCell


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_inferred((self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_inferred((4 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        H = self._hidden_size
        pre = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * H) + \
            F.FullyConnected(states[0], h2h_weight, h2h_bias,
                             num_hidden=4 * H)
        i, f, g, o = F.split(pre, num_outputs=4, axis=1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c = f * states[1] + i * g
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_inferred((3 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        H = self._hidden_size
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * H)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=3 * H)
        xr, xz, xn = F.split(i2h, num_outputs=3, axis=1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.tanh(xn + r * hn)
        out = (1 - z) * n + z * states[0]
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def __call__(self, x, states):
        return self.forward(x, states)

    def forward(self, x, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[pos:pos + n]
            pos += n
            x, state = cell(x, state)
            next_states.extend(state)
        return x, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        if base_cell._modified:
            raise MXNetError(
                f"cell {base_cell.name} is already wrapped by a modifier "
                "cell; double-wrapping (e.g. Zoneout(Zoneout(c))) is not "
                "allowed")
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + "mod_", params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def __call__(self, x, states):
        from ... import ndarray as F
        if self._rate > 0:
            x = F.Dropout(x, p=self._rate, axes=self._axes)
        return x, states

    forward = __call__


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, x, states):
        from ... import autograd, ndarray as F
        out, next_states = self.base_cell(x, states)
        if autograd.is_training():
            if self.zoneout_outputs > 0:
                mask = F.Dropout(F.ones_like(out), p=self.zoneout_outputs,
                                 mode="always") * (1 - self.zoneout_outputs)
                prev = self._prev_output if self._prev_output is not None \
                    else F.zeros_like(out)
                out = F.where(mask, out, prev)
            if self.zoneout_states > 0:
                masked = []
                for new, old in zip(next_states, states):
                    mask = F.Dropout(F.ones_like(new), p=self.zoneout_states,
                                     mode="always") * (1 - self.zoneout_states)
                    masked.append(F.where(mask, new, old))
                next_states = masked
        self._prev_output = out
        return out, next_states

    forward = __call__


class ResidualCell(ModifierCell):
    def __call__(self, x, states):
        out, states = self.base_cell(x, states)
        return out + x, states

    forward = __call__


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size)
                + self._children["r_cell"].state_info(batch_size))

    def __call__(self, x, states):
        raise MXNetError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.state_info(batch_size) and \
                self.begin_state(batch_size)
        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(
            length, inputs, begin_state[:nl], layout, True, valid_length)
        # reverse only within each sequence's valid region so the backward
        # pass never sees padding first (ref: SequenceReverse with
        # use_sequence_length in BidirectionalCell.unroll)
        rev = F.SequenceReverse(inputs, valid_length,
                                use_sequence_length=valid_length is not None,
                                axis=axis)
        r_out, r_states = r_cell.unroll(
            length, rev, begin_state[nl:], layout, True, valid_length)
        r_out = F.SequenceReverse(r_out, valid_length,
                                  use_sequence_length=valid_length is not None,
                                  axis=axis)
        out = F.Concat(l_out, r_out, dim=2)
        if not merge_outputs and merge_outputs is not None:
            out = list(F.split(out, num_outputs=length, axis=axis,
                               squeeze_axis=True)) if length > 1 else \
                [F.squeeze(out, axis=axis)]
        return out, l_states + r_states
