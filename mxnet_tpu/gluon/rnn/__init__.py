from .rnn_layer import RNN, LSTM, GRU
from .rnn_cell import (BidirectionalCell, DropoutCell, GRUCell, HybridRecurrentCell,
                       LSTMCell, ModifierCell, RecurrentCell, ResidualCell,
                       RNNCell, SequentialRNNCell, ZoneoutCell)
