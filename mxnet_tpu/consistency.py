"""Accelerator-backend consistency sweep: TPU vs CPU numerics.

The reference replays its *entire* CPU unit suite on the accelerator
(ref: tests/python/gpu/test_operator_gpu.py:1 imports the whole unittest
dir) and cross-checks per-op outputs between contexts with
``check_consistency`` (ref: python/mxnet/test_utils.py:1261). That full
replay costs ~40 min per backend; the TPU-side equivalent here is a
compact table-driven sweep — ~50 representative ops spanning every
kernel family (elementwise, reduction, matmul/MXU, conv, norm, indexing,
sorting, linalg, sequence, loss) plus one model-zoo forward — run on the
real chip and compared against CPU jax within dtype-scaled tolerance.

``bench.py`` folds ``run_sweep()`` into the driver bench so every chip
window revalidates numerics (bf16 MXU matmul semantics, conv algorithm
differences, int8 saturation) alongside throughput; the pass/fail tally
ships in the bench JSON.
"""
from __future__ import annotations

import numpy as np

__all__ = ["OP_TABLE", "run_sweep", "model_forward_consistency"]


def _r(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32)


def _pos(rng, *shape):
    return np.abs(_r(rng, *shape)) + 0.1


def _build_table():
    """Each row: (name, fn(nd, *inputs) -> NDArray, inputs_builder(rng),
    {tol overrides}). Inputs are host numpy; the harness places them on
    each device context and diffs the outputs."""
    t = []

    def add(name, fn, builder, **tol):
        t.append((name, fn, builder, tol))

    # elementwise unary (VPU lanes)
    add("exp", lambda nd, a: nd.exp(a), lambda r: [_r(r, 32, 33)])
    add("log", lambda nd, a: nd.log(a), lambda r: [_pos(r, 32, 33)])
    add("sqrt", lambda nd, a: nd.sqrt(a), lambda r: [_pos(r, 32, 33)])
    add("rsqrt", lambda nd, a: nd.rsqrt(a), lambda r: [_pos(r, 32, 33)])
    add("sigmoid", lambda nd, a: nd.sigmoid(a), lambda r: [_r(r, 32, 33)])
    add("tanh", lambda nd, a: nd.tanh(a), lambda r: [_r(r, 32, 33)])
    add("erf", lambda nd, a: nd.erf(a), lambda r: [_r(r, 32, 33)])
    add("relu", lambda nd, a: nd.relu(a), lambda r: [_r(r, 32, 33)])
    add("gamma", lambda nd, a: nd.gamma(a), lambda r: [_pos(r, 16, 17)],
        rtol=1e-4)
    add("expm1", lambda nd, a: nd.expm1(a), lambda r: [_r(r, 32, 33)])
    add("sin", lambda nd, a: nd.sin(a), lambda r: [_r(r, 32, 33)])
    add("arctan", lambda nd, a: nd.arctan(a), lambda r: [_r(r, 32, 33)])

    # elementwise binary / broadcast
    add("broadcast_add", lambda nd, a, b: nd.broadcast_add(a, b),
        lambda r: [_r(r, 16, 1, 8), _r(r, 1, 5, 8)])
    add("broadcast_mul", lambda nd, a, b: nd.broadcast_mul(a, b),
        lambda r: [_r(r, 16, 1, 8), _r(r, 1, 5, 8)])
    add("broadcast_div", lambda nd, a, b: nd.broadcast_div(a, b),
        lambda r: [_r(r, 16, 8), _pos(r, 16, 8)])
    add("broadcast_power", lambda nd, a, b: nd.broadcast_power(a, b),
        lambda r: [_pos(r, 16, 8), _r(r, 16, 8)], rtol=1e-4)
    add("broadcast_maximum", lambda nd, a, b: nd.broadcast_maximum(a, b),
        lambda r: [_r(r, 16, 8), _r(r, 16, 8)])
    add("where", lambda nd, c, a, b: nd.where(c, a, b),
        lambda r: [(_r(r, 16, 8) > 0).astype(np.float32),
                   _r(r, 16, 8), _r(r, 16, 8)])
    add("clip", lambda nd, a: nd.clip(a, -0.5, 0.5),
        lambda r: [_r(r, 32, 33)])
    add("smooth_l1", lambda nd, a: nd.smooth_l1(a, scalar=1.0),
        lambda r: [_r(r, 32, 33)])

    # reductions
    add("sum_axis", lambda nd, a: nd.sum(a, axis=1),
        lambda r: [_r(r, 64, 65)], rtol=1e-4, atol=1e-4)
    add("mean", lambda nd, a: nd.mean(a, axis=(0, 2)),
        lambda r: [_r(r, 16, 17, 18)], rtol=1e-4, atol=1e-5)
    add("max_axis", lambda nd, a: nd.max(a, axis=0),
        lambda r: [_r(r, 64, 65)])
    add("prod", lambda nd, a: nd.prod(a, axis=1),
        lambda r: [1.0 + 0.01 * _r(r, 32, 16)], rtol=1e-4)
    add("norm", lambda nd, a: nd.norm(a, ord=2, axis=1),
        lambda r: [_r(r, 32, 64)], rtol=1e-4)
    add("argmax", lambda nd, a: nd.argmax(a, axis=1),
        lambda r: [_r(r, 32, 65)])
    add("nansum", lambda nd, a: nd.nansum(a, axis=0),
        lambda r: [_r(r, 32, 16)], rtol=1e-4, atol=1e-5)

    # matmul family — the MXU path, the one most likely to diverge
    add("dot", lambda nd, a, b: nd.dot(a, b),
        lambda r: [_r(r, 128, 256), _r(r, 256, 128)],
        rtol=2e-4, atol=2e-3)
    add("dot_transpose", lambda nd, a, b: nd.dot(a, b, transpose_b=True),
        lambda r: [_r(r, 64, 256), _r(r, 64, 256)],
        rtol=2e-4, atol=2e-3)
    add("batch_dot", lambda nd, a, b: nd.batch_dot(a, b),
        lambda r: [_r(r, 8, 64, 96), _r(r, 8, 96, 64)],
        rtol=2e-4, atol=2e-3)
    add("FullyConnected",
        lambda nd, x, w, b: nd.FullyConnected(x, w, b, num_hidden=64),
        lambda r: [_r(r, 32, 128), _r(r, 64, 128), _r(r, 64)],
        rtol=2e-4, atol=2e-3)
    add("linalg_gemm2", lambda nd, a, b: nd.linalg_gemm2(a, b),
        lambda r: [_r(r, 64, 64), _r(r, 64, 64)], rtol=2e-4, atol=2e-3)

    # convolution / pooling — algorithm choice differs per backend
    add("Convolution",
        lambda nd, x, w, b: nd.Convolution(
            x, w, b, kernel=(3, 3), num_filter=16, pad=(1, 1)),
        lambda r: [_r(r, 4, 8, 14, 14), _r(r, 16, 8, 3, 3), _r(r, 16)],
        rtol=5e-4, atol=5e-3)
    add("Convolution_stride2",
        lambda nd, x, w, b: nd.Convolution(
            x, w, b, kernel=(3, 3), num_filter=8, stride=(2, 2)),
        lambda r: [_r(r, 2, 4, 15, 15), _r(r, 8, 4, 3, 3), _r(r, 8)],
        rtol=5e-4, atol=5e-3)
    add("Deconvolution",
        lambda nd, x, w: nd.Deconvolution(
            x, w, kernel=(2, 2), num_filter=4, stride=(2, 2),
            no_bias=True),
        lambda r: [_r(r, 2, 8, 7, 7), _r(r, 8, 4, 2, 2)],
        rtol=5e-4, atol=5e-3)
    add("Pooling_max",
        lambda nd, x: nd.Pooling(x, kernel=(2, 2), pool_type="max",
                                 stride=(2, 2)),
        lambda r: [_r(r, 4, 8, 14, 14)])
    add("Pooling_avg",
        lambda nd, x: nd.Pooling(x, kernel=(2, 2), pool_type="avg",
                                 stride=(2, 2)),
        lambda r: [_r(r, 4, 8, 14, 14)], rtol=1e-4)

    # normalization / activation blocks
    add("BatchNorm",
        lambda nd, x, g, b, m, v: nd.BatchNorm(
            x, g, b, m, v, fix_gamma=False, use_global_stats=True),
        lambda r: [_r(r, 8, 16, 7, 7), _pos(r, 16), _r(r, 16),
                   _r(r, 16), _pos(r, 16)], rtol=1e-4, atol=1e-4)
    add("LayerNorm",
        lambda nd, x, g, b: nd.LayerNorm(x, g, b),
        lambda r: [_r(r, 16, 64), _pos(r, 64), _r(r, 64)],
        rtol=1e-4, atol=1e-4)
    add("L2Normalization", lambda nd, x: nd.L2Normalization(x),
        lambda r: [_r(r, 16, 64)], rtol=1e-4)
    add("LRN", lambda nd, x: nd.LRN(x, nsize=5),
        lambda r: [_r(r, 4, 8, 7, 7)], rtol=1e-4)
    add("softmax", lambda nd, a: nd.softmax(a, axis=-1),
        lambda r: [_r(r, 32, 65)], rtol=1e-4, atol=1e-5)
    add("log_softmax", lambda nd, a: nd.log_softmax(a, axis=-1),
        lambda r: [_r(r, 32, 65)], rtol=1e-4, atol=1e-4)
    add("LeakyReLU_elu",
        lambda nd, a: nd.LeakyReLU(a, act_type="elu", slope=0.3),
        lambda r: [_r(r, 32, 33)])

    # shape / indexing / gather-scatter
    add("transpose", lambda nd, a: nd.transpose(a, axes=(2, 0, 1)),
        lambda r: [_r(r, 8, 9, 10)])
    add("take", lambda nd, a, idx: nd.take(a, idx),
        lambda r: [_r(r, 50, 8),
                   r.integers(0, 50, (16,)).astype(np.float32)])
    add("gather_nd", lambda nd, a, idx: nd.gather_nd(a, idx),
        lambda r: [_r(r, 6, 7),
                   r.integers(0, 6, (2, 5)).astype(np.float32)])
    add("Embedding",
        lambda nd, idx, w: nd.Embedding(idx, w, input_dim=50,
                                        output_dim=16),
        lambda r: [r.integers(0, 50, (8, 4)).astype(np.float32),
                   _r(r, 50, 16)])
    add("one_hot", lambda nd, idx: nd.one_hot(idx, depth=10),
        lambda r: [r.integers(0, 10, (16,)).astype(np.float32)])
    add("slice", lambda nd, a: nd.slice(a, begin=(1, 2), end=(7, 9)),
        lambda r: [_r(r, 8, 10)])
    add("reverse", lambda nd, a: nd.reverse(a, axis=1),
        lambda r: [_r(r, 8, 10)])
    add("tile", lambda nd, a: nd.tile(a, reps=(2, 3)),
        lambda r: [_r(r, 4, 5)])
    add("space_to_depth", lambda nd, a: nd.space_to_depth(a, block_size=2),
        lambda r: [_r(r, 2, 4, 6, 6)])
    add("pick", lambda nd, a, idx: nd.pick(a, idx, axis=1),
        lambda r: [_r(r, 16, 10),
                   r.integers(0, 10, (16,)).astype(np.float32)])

    # sorting / topk
    add("sort", lambda nd, a: nd.sort(a, axis=1),
        lambda r: [_r(r, 16, 33)])
    add("topk", lambda nd, a: nd.topk(a, k=5, axis=1, ret_typ="value"),
        lambda r: [_r(r, 16, 33)])
    add("argsort", lambda nd, a: nd.argsort(a, axis=1),
        lambda r: [r.permutation(33 * 16).reshape(16, 33)
                   .astype(np.float32)])

    # linalg
    add("linalg_potrf", lambda nd, a: nd.linalg_potrf(a),
        lambda r: [_spd(r, 16)], rtol=1e-3, atol=1e-3)
    add("linalg_inverse", lambda nd, a: nd.linalg_inverse(a),
        lambda r: [_spd(r, 12)], rtol=1e-3, atol=1e-3)
    add("linalg_det", lambda nd, a: nd.linalg_det(a),
        lambda r: [_spd(r, 8)], rtol=1e-3)

    # sequence / loss ops
    add("SequenceMask",
        lambda nd, x, l: nd.SequenceMask(x, l, use_sequence_length=True),
        lambda r: [_r(r, 6, 4, 8),
                   np.array([2, 4, 6, 3], np.float32)])
    add("softmax_cross_entropy",
        lambda nd, x, l: nd.softmax_cross_entropy(x, l),
        lambda r: [_r(r, 16, 10),
                   r.integers(0, 10, (16,)).astype(np.float32)],
        rtol=1e-4, atol=1e-4)
    add("ctc_loss",
        lambda nd, x, l: nd.ctc_loss(x, l),
        lambda r: [_r(r, 10, 4, 6),
                   np.array([[1, 2, 0], [2, 3, 1], [1, 1, 0],
                             [4, 2, 3]], np.float32)],
        rtol=1e-3, atol=1e-3)
    return t


def _spd(rng, n):
    a = _r(rng, n, n)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


OP_TABLE = _build_table()

# dtype-scaled default tolerances, mirroring the reference's
# check_consistency per-dtype eps ladder
# (ref: python/mxnet/test_utils.py:1261 tol={np.dtype(np.float16): 1e-1,
# np.dtype(np.float32): 1e-3, ...})
_DEFAULT_TOL = {
    "float32": dict(rtol=1e-5, atol=1e-5),
    "bfloat16": dict(rtol=3e-2, atol=3e-2),
}


def _run_one(name, fn, builder, tol, dtype, seed=0):
    from . import nd as _nd
    from .context import cpu, tpu

    rng = np.random.default_rng(seed)
    inputs = builder(rng)
    base = dict(_DEFAULT_TOL[dtype])
    base.update(tol)
    outs = []
    for ctx in (cpu(), tpu()):
        arrs = []
        for x in inputs:
            a = _nd.array(x, ctx=ctx)
            if dtype != "float32" and not _is_index_input(x):
                a = a.astype(dtype)
            arrs.append(a)
        out = fn(_nd, *arrs)
        outs.append(np.asarray(out.astype("float32").asnumpy()))
    np.testing.assert_allclose(outs[0], outs[1], **base)
    return outs


def _is_index_input(x):
    # integer-valued index tensors must not be cast to bf16 (precision
    # loss would change the indices themselves)
    return np.allclose(x, np.round(x)) and np.all(np.abs(x) < 1e4)


def run_sweep(dtype="float32", ops=None, seed=0):
    """Run the table on cpu-vs-accelerator contexts; returns a summary
    dict {"total", "pass", "fail", "failures": [(name, err), ...],
    "rows": [{"name", "ok", "fingerprint"}, ...]}.

    Each passing row is stamped with the CPU-side output's drift
    fingerprint (``profiling.health.fingerprint_params``) — one
    vocabulary with the bit-identical-resume tests and the chaos
    suite's bounded-drift checks, so two chip windows (or two
    backends) can diff per-op numerics without re-running the peer.

    On a CPU-only host both contexts resolve to the same device and the
    sweep degenerates to a harness self-test (exactly how the reference's
    gpu suite behaves when run on a CPU-only build)."""
    from .profiling.health import fingerprint_params

    table = OP_TABLE if ops is None else [
        row for row in OP_TABLE if row[0] in ops]
    failures = []
    rows = []
    for name, fn, builder, tol in table:
        row = {"name": name, "ok": True, "fingerprint": None}
        try:
            outs = _run_one(name, fn, builder, tol, dtype, seed=seed)
            # fingerprint the REFERENCE (cpu-context) output: the
            # stable side a later chip row is compared against
            row["fingerprint"] = fingerprint_params({"out": outs[0]})
        except Exception as e:  # noqa: BLE001 — tally, don't abort sweep
            row["ok"] = False
            failures.append((name, str(e).splitlines()[0][:160]
                             if str(e) else repr(e)))
        rows.append(row)
    return {
        "total": len(table),
        "pass": len(table) - len(failures),
        "fail": len(failures),
        "failures": failures,
        "rows": rows,
    }


def model_forward_consistency(batch=4, rtol=2e-2, atol=2e-2):
    """One model-zoo forward (resnet18_v1) on cpu vs accelerator, fp32.

    The per-op table can miss composition effects (layout passes, fusion,
    accumulated bf16 rounding through 18 layers); the model-level check
    is the reference's test_gluon_model_zoo_gpu analogue
    (ref: tests/python/gpu/test_gluon_model_zoo_gpu.py:55)."""
    import jax

    from .context import cpu, tpu
    from .gluon.block import infer_shapes
    from .gluon.model_zoo import vision
    from .ndarray.ndarray import NDArray

    net = vision.resnet18_v1()
    net.initialize()
    infer_shapes(net, (batch, 3, 32, 32))
    x = np.random.default_rng(0).standard_normal(
        (batch, 3, 32, 32)).astype(np.float32)
    outs = []
    for ctx in (cpu(), tpu()):
        dev = ctx.jax_device
        xs = NDArray(jax.device_put(np.asarray(x), dev))
        with jax.default_device(dev):
            out = net(xs)
        outs.append(np.asarray(out.asnumpy()))
    np.testing.assert_allclose(outs[0], outs[1], rtol=rtol, atol=atol)
    return True
