"""Hang flight recorder: the last-N + in-flight span view, dumped with
thread stacks when the process wedges.

Motivation (ISSUE 5): five bench rounds in a row died as ``rc=124`` /
"tunnel probe failed (wedged backend init?)" with zero causal signal.
The tracing rings already hold what was in flight; this module gets
that record OUT of a process that is about to die or already hung:

- :func:`dump` — JSON dump of every thread's open (unclosed) spans,
  its recent closed spans, and formatted Python stacks for all threads;
  written atomically to a file, or to stderr.
- :func:`install` — arms the exits: ``faulthandler.enable()`` for
  C-level crashes (SIGSEGV/SIGABRT print stacks), a chained SIGTERM
  handler and a chained ``sys.excepthook`` that write the dump first.
  NOT installed at import: signal handlers are process policy, so the
  entrypoints that own the process (bench.py, tools/launch.py roles)
  opt in.
- :class:`Watchdog` / :func:`arm` — a daemon thread that fires a dump
  when no span opens/closes for ``MXTPU_HANG_TIMEOUT_SEC`` seconds (a
  healthy training loop closes spans constantly; a wedged one goes
  silent). One dump per stall: it re-arms when activity resumes.

The dump is bounded (``max_spans`` per thread) so it can be embedded
in a failure artifact — bench.py folds it into the failure JSON.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback

from ..base import get_env
from . import clock


def default_dump_path():
    """MXTPU_FLIGHT_PATH, else None (dump to stderr)."""
    return os.environ.get("MXTPU_FLIGHT_PATH") or None


def hang_timeout_sec():
    return get_env("MXTPU_HANG_TIMEOUT_SEC", 0.0, float)


def _attrs_view(attrs):
    """Bounded copy of a span attrs dict. Open spans belong to LIVE
    threads that may resize the dict mid-iteration — retry, then give
    up rather than raise out of a dump."""
    for _ in range(3):
        try:
            return {k: str(v)[:80] for k, v in list(attrs.items())}
        except RuntimeError:       # dict changed size during iteration
            continue
    return {"_torn": "attrs mutating during dump"}


def _fmt_span(s, now_ns):
    """Bounded view of one span dict / open Span object."""
    if isinstance(s, dict):
        return {"name": s["name"], "cat": s.get("cat"),
                "trace": "%016x" % (s.get("trace") or 0),
                "span": "%016x" % (s.get("span") or 0),
                "dur_ms": round(s["dur_ns"] / 1e6, 3),
                "attrs": _attrs_view(s.get("attrs") or {})}
    return {"name": s.name, "cat": s.cat,
            "trace": "%016x" % s.trace_id, "span": "%016x" % s.span_id,
            "open_ms": round((now_ns - s.start_ns) / 1e6, 3),
            "attrs": _attrs_view(s.attrs)}


def snapshot(max_spans=10):
    """Bounded dict of the rings: per thread, the in-flight (unclosed)
    span stack outermost-first and the most recent closed spans."""
    from . import rings, last_activity_ns
    now = clock.now_ns()
    threads = []
    for name, ident, closed, open_spans in rings():
        if not closed and not open_spans:
            continue
        threads.append({
            "thread": name, "tid": ident,
            "in_flight": [_fmt_span(s, now) for s in open_spans],
            "recent": [_fmt_span(s, now) for s in closed[-max_spans:]],
        })
    return {
        "ts": time.time(),
        "monotonic_ns": now,
        "idle_ms": round((now - last_activity_ns()) / 1e6, 1),
        "pid": os.getpid(),
        "role": os.environ.get("DMLC_ROLE"),
        "threads": threads,
    }


def thread_stacks(limit=40):
    """{thread_name_or_id: formatted stack} for every live thread —
    the pure-Python half of faulthandler (string-valued, embeddable)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = "%s (%d)" % (names.get(ident, "?"), ident)
        out[label] = "".join(traceback.format_stack(frame, limit=limit))
    return out


def dump(reason, path=None, max_spans=10, file=None):
    """Assemble and emit one flight-recorder dump. Returns the dict.

    ``path`` (or MXTPU_FLIGHT_PATH) writes atomically; otherwise the
    dump goes to ``file`` (default stderr) as indented JSON between
    marker lines so log scrapers can cut it out."""
    doc = snapshot(max_spans=max_spans)
    doc["reason"] = str(reason)[:300]
    doc["stacks"] = thread_stacks()
    path = path or default_dump_path()
    text = json.dumps(doc, indent=1, sort_keys=True)
    if path:
        try:
            tmp = "%s.tmp.%d" % (path, os.getpid())
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text)
            os.replace(tmp, path)
            return doc
        except OSError:
            pass               # fall through to stderr: never lose it
    f = file or sys.stderr
    print("=== MXTPU FLIGHT RECORDER (%s) ===" % doc["reason"], file=f)
    print(text, file=f)
    print("=== END FLIGHT RECORDER ===", file=f, flush=True)
    return doc


# -- exit hooks --------------------------------------------------------------
_installed = [False]
_prev_sigterm = [None]
_prev_excepthook = [None]


def _on_sigterm(signum, frame):
    try:
        dump("SIGTERM")
    except Exception:  # noqa: BLE001 — the dump must never mask the exit
        pass
    prev = _prev_sigterm[0]
    if callable(prev):
        prev(signum, frame)    # e.g. kvstore snapshot, PreemptionGuard
    elif prev == signal.SIG_DFL:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _on_excepthook(exc_type, exc, tb):
    try:
        if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
            dump("unhandled %s: %s" % (exc_type.__name__,
                                       str(exc)[:200]))
    except Exception:  # noqa: BLE001
        pass
    (_prev_excepthook[0] or sys.__excepthook__)(exc_type, exc, tb)


def install(signals=True, excepthook=True, watchdog=None):
    """Arm the flight recorder's exits (idempotent). ``watchdog``:
    None honors MXTPU_HANG_TIMEOUT_SEC (>0 arms), a number arms with
    that timeout, False skips. Call from process entrypoints that own
    signal policy (bench.py does)."""
    if not _installed[0]:
        _installed[0] = True
        import faulthandler
        if not faulthandler.is_enabled():
            try:
                faulthandler.enable()   # SIGSEGV/SIGABRT/SIGBUS stacks
            except (RuntimeError, OSError, ValueError):
                pass                    # no usable stderr fd
        if signals:
            try:
                _prev_sigterm[0] = signal.getsignal(signal.SIGTERM)
                signal.signal(signal.SIGTERM, _on_sigterm)
            except (ValueError, OSError):   # not the main thread
                pass
        if excepthook:
            _prev_excepthook[0] = sys.excepthook
            sys.excepthook = _on_excepthook
    if watchdog is None:
        t = hang_timeout_sec()
        if t > 0:
            arm(t)
    elif watchdog:
        arm(float(watchdog))


# -- watchdog ----------------------------------------------------------------
class Watchdog(threading.Thread):
    """Daemon thread firing one dump per stall: no span open/close (and
    no :func:`heartbeat`) for ``timeout`` seconds."""

    def __init__(self, timeout, path=None, on_fire=None):
        super().__init__(name="mxtpu-hang-watchdog", daemon=True)
        self.timeout = float(timeout)
        self.path = path
        self.on_fire = on_fire
        self.fired = 0
        self._stop = threading.Event()

    def run(self):
        from . import last_activity_ns
        fired_at = None            # activity watermark of the last dump
        poll = min(max(self.timeout / 4.0, 0.05), 1.0)
        while not self._stop.wait(poll):
            last = last_activity_ns()
            idle = (clock.now_ns() - last) / 1e9
            if idle < self.timeout:
                continue
            if fired_at == last:
                continue           # same stall, already dumped
            fired_at = last
            self.fired += 1
            try:
                doc = dump("hang: no span activity for %.1fs "
                           "(MXTPU_HANG_TIMEOUT_SEC=%g)"
                           % (idle, self.timeout), path=self.path)
            except Exception:  # noqa: BLE001 — a racing/failing dump
                continue       # must never kill the watchdog thread
            if self.on_fire is not None:
                try:
                    self.on_fire(doc)
                except Exception:  # noqa: BLE001 — observer only
                    pass

    def stop(self):
        self._stop.set()


_watchdog = [None]


def heartbeat():
    """Mark forward progress without opening a span (bench stage
    boundaries, long pure-compute sections)."""
    from . import _touch
    _touch()


def arm(timeout=None, path=None, on_fire=None):
    """Start (or restart) the process hang watchdog. ``timeout``
    defaults to MXTPU_HANG_TIMEOUT_SEC; <= 0 only disarms. Refuses
    (with a warning) when tracing is disabled — no span ever touches
    the activity clock then, so the watchdog would cry hang on every
    healthy stretch longer than the timeout."""
    disarm()
    if timeout is None:
        timeout = hang_timeout_sec()
    if timeout <= 0:
        return None
    from . import enabled
    if not enabled():
        print("mxtpu: hang watchdog NOT armed: tracing is disabled "
              "(MXTPU_TRACE_SAMPLE=0), so no span activity would ever "
              "reset it", file=sys.stderr)
        return None
    heartbeat()                   # arming is progress: time from NOW
    w = Watchdog(timeout, path=path, on_fire=on_fire)
    w.start()
    _watchdog[0] = w
    return w


def disarm():
    w, _watchdog[0] = _watchdog[0], None
    if w is not None:
        w.stop()
    return w
