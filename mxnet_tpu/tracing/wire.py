"""Kvstore wire propagation — the server half.

Wire format v2 (comm.cc): every request header ends with
``u64 trace_id | u64 span_id``; zeros mean untraced. The worker side
stamps them per request (kvstore/dist.py ``WorkerConnection._call``
calls ``mxtpu_client_set_trace`` inside its span); this module is what
the SERVER process installs so those ids become spans on its side:

- :func:`install_server_sink` registers a ctypes callback the C++
  connection threads invoke once per traced request, with recv/done
  CLOCK_MONOTONIC ns timestamps measured natively. Each call lands one
  ``server_recv:<op>`` span in the server's tracing rings, parented to
  the worker's span id — the cross-process child edge trace_merge
  stitches on.
- :func:`server_parent_ctx` reads the trace context of the request the
  CURRENT native connection thread is handling (thread-local in C++),
  so the Python optimizer updater can parent its ``server_update`` span
  to the worker push that completed the round.
"""
from __future__ import annotations

import ctypes

from .. import _native
from . import record_span, enabled

OP_NAMES = {1: "init", 2: "push", 3: "pull", 4: "barrier", 5: "command",
            6: "push_2bit", 7: "pull_rows"}

_installed = [False]


def _sink(op, key, req_id, rank, trace_id, span_id, recv_ns, done_ns):
    # runs on a C++ connection thread (ctypes grabs the GIL); must
    # never raise across the C boundary
    try:
        if not enabled() or not trace_id:
            return
        record_span(
            "server_recv:%s" % OP_NAMES.get(int(op), str(op)),
            trace_id, span_id, recv_ns, done_ns, cat="comm",
            attrs={"role": "server", "key": int(key),
                   "rank": int(rank), "req_id": int(req_id)})
    except Exception:  # noqa: BLE001 — tracing must not kill the server
        pass


def install_server_sink(lib=None):
    """Install the trace sink on the native transport (idempotent).
    Called by kvstore/dist.py run_server and by in-process tests."""
    if _installed[0]:
        return
    _installed[0] = True
    _native.set_server_trace_sink(_sink, lib=lib)


def server_parent_ctx(lib=None):
    """(trace_id, span_id) of the request being handled on this native
    connection thread — (0, 0) outside a traced request."""
    if lib is None:
        lib = _native.load_comm()
    tid = ctypes.c_uint64(0)
    sid = ctypes.c_uint64(0)
    lib.mxtpu_server_current_trace(ctypes.byref(tid), ctypes.byref(sid))
    return (int(tid.value), int(sid.value))
