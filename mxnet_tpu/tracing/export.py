"""Trace-file export/load + chrome-trace conversion.

One process writes ONE trace file; ``tools/trace_merge.py`` stitches
the per-rank files of a distributed job into a single Perfetto-loadable
timeline with clock alignment and a straggler report.

File format (versioned, plain JSON)::

    {"version": 1,
     "clock": "monotonic_ns",           # absolute CLOCK_MONOTONIC
     "meta": {"pid": ..., "role": "worker", "rank": 0,
              "epoch_ns": <process epoch for profiler-relative ts>},
     "spans": [{"name", "cat", "trace", "span", "parent",
                "start_ns", "dur_ns", "tid", "thread", "attrs"}, ...]}

Wire-propagation format this pairs with (comm.cc wire v2): every
kvstore request header carries ``u64 trace_id | u64 span_id`` after the
fixed fields; 0 = untraced. The format is versioned by the transport's
source — both sides build from one comm.cc, and the v2 header growth
bumped the rendezvous magic ("MXTW" -> "MXT2") so a mixed v1/v2
pair fails fast at handshake; a future header change must bump it
again.
"""
from __future__ import annotations

import json
import os

from ..base import MXNetError
from . import clock

TRACE_VERSION = 1


def default_path():
    """MXTPU_TRACE_FILE, or trace.<role><rank>.json inside a launch.py
    job (processes share a cwd), else trace.json."""
    path = os.environ.get("MXTPU_TRACE_FILE")
    if path:
        return path
    role = os.environ.get("DMLC_ROLE")
    if role is None:
        return "trace.json"
    idx = os.environ.get("DMLC_SERVER_ID" if role == "server"
                         else "DMLC_WORKER_ID", "0")
    return "trace.%s%s.json" % (role, idx)


def _proc_meta():
    meta = {"pid": os.getpid(), "epoch_ns": clock.EPOCH_NS}
    role = os.environ.get("DMLC_ROLE")
    if role is not None:
        meta["role"] = role
        meta["rank"] = int(os.environ.get(
            "DMLC_SERVER_ID" if role == "server" else "DMLC_WORKER_ID",
            "0"))
    return meta


def trace_doc(spans=None, meta=None):
    from . import spans_snapshot
    doc = {"version": TRACE_VERSION, "clock": "monotonic_ns",
           "meta": _proc_meta(),
           "spans": spans if spans is not None else spans_snapshot()}
    if meta:
        doc["meta"].update(meta)
    return doc


def write_trace(path=None, spans=None, meta=None):
    """Write the process's recorded spans to ``path`` (atomically:
    tmp+rename, like telemetry exports). Returns the document."""
    path = path or default_path()
    doc = trace_doc(spans, meta)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc


def load_trace(path):
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "spans" not in doc:
        raise MXNetError("%s is not a trace file (no 'spans' key)" % path)
    if doc.get("version", 0) > TRACE_VERSION:
        raise MXNetError(
            "trace file %s is version %s, this build reads <= %d"
            % (path, doc.get("version"), TRACE_VERSION))
    return doc


_pull_nonce = [0]


def pull_server_trace(kv, path, timeout=10.0, poll=0.05):
    """Fetch a kvstore SERVER process's trace file through the profiler
    directive channel ({"cmd": "trace_dump"} — the tracing analogue of
    telemetry.export.pull_server_metrics; same shared-filesystem
    contract). Returns the loaded trace document."""
    import time
    conn = getattr(kv, "_conn", None) or kv
    send = getattr(conn, "send_profiler_command", None)
    if send is None:
        raise MXNetError(
            "pull_server_trace needs a connected dist kvstore "
            "(create mx.kv.create('dist_sync') first)")
    _pull_nonce[0] += 1
    nonce_path = "%s.req%d.%d" % (path, os.getpid(), _pull_nonce[0])
    send({"cmd": "trace_dump", "path": nonce_path})
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            doc = load_trace(nonce_path)
        except (OSError, ValueError, MXNetError):
            time.sleep(poll)
            continue
        os.replace(nonce_path, path)
        return doc
    raise MXNetError(
        "server trace dump did not appear at %s within %.1fs (server "
        "down, tracing disabled there, or path not shared?)"
        % (nonce_path, timeout))


def attribution_events(attrib_doc, pid=90, tid=0):
    """Cost-attribution rows (a ``profiling`` ledger/attribution
    document) rendered as a chrome-trace flame strip: one 'X' event
    per op, laid end-to-end in rank order on a dedicated pid, sized by
    measured (preferred) or roofline-estimated per-step seconds. Not a
    timeline — a proportional-width ranking that sits next to the real
    spans in the same Perfetto view, so "where does the step go" and
    "when did it go there" read off one artifact."""
    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": tid,
               "args": {"name": "op attribution (per step)"}}]
    cursor = 0.0
    for g in attrib_doc.get("by_op", []):
        dur_us = (g.get("measured_s") or g.get("est_s") or 0.0) * 1e6
        if dur_us <= 0:
            continue
        args = {"flops": g.get("flops", 0), "bytes": g.get("bytes", 0),
                "bound": g.get("bound", "?")}
        if g.get("rule"):
            args["rule"] = g["rule"]
        if g.get("mfu") is not None:
            args["mfu"] = g.get("mfu")
        events.append({
            "name": g.get("op") or "?", "cat": "attribution", "ph": "X",
            "ts": cursor, "dur": dur_us, "pid": pid, "tid": tid,
            "args": args})
        cursor += dur_us
    return events


def memory_counter_events(census_doc, pid=91, ts=0.0):
    """A live-array census (``profiling.memory.live_census`` document)
    rendered as Perfetto counter tracks: one stacked 'C' counter of
    live bytes by role, plus one counter per device with its total —
    the memory analogue of :func:`attribution_events`. ``ts`` places
    the sample on the shared clock (callers pass the profiler's
    now)."""
    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
               "args": {"name": "HBM live bytes (census)"}}]
    by_role = census_doc.get("by_role", {})
    if by_role:
        events.append({
            "name": "mx_memory_live_bytes", "ph": "C", "ts": ts,
            "pid": pid,
            "args": {role: r.get("bytes", 0)
                     for role, r in sorted(by_role.items())}})
    for dev, d in sorted(census_doc.get("by_device", {}).items()):
        events.append({
            "name": "mx_memory_live_bytes[%s]" % dev, "ph": "C",
            "ts": ts, "pid": pid,
            "args": {"bytes": d.get("total_bytes", 0)}})
    return events


def health_counter_events(health_doc, pid=92, ts=0.0):
    """A model-health summary (``profiling.health.snapshot_doc``
    document) rendered as Perfetto counter tracks beside the PR 7
    memory track: loss + loss EWMA, global grad norm, and cumulative
    nonfinite count (stacked by seam) on the shared clock."""
    def _finite(v):
        # a NaN/Inf loss is exactly what an unhealthy run carries, and
        # json.dumps would emit bare NaN/Infinity literals that make
        # Perfetto reject the whole trace — drop the sample, keep the
        # nonfinite-count track as the signal. (Local copy by design:
        # tracing/ must import standalone, without telemetry; the
        # sibling guards live in telemetry/export._json_safe and
        # tools/perf_gate._is_finite_number.)
        return isinstance(v, (int, float)) and not isinstance(v, bool) \
            and v == v and v not in (float("inf"), float("-inf"))

    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
               "args": {"name": "model health (sentry/loss/norms)"}}]
    loss = health_doc.get("loss", {})
    args = {}
    if _finite(loss.get("last")):
        args["loss"] = loss["last"]
    if _finite(loss.get("ewma")):
        args["ewma"] = loss["ewma"]
    if args:
        events.append({"name": "mx_health_loss", "ph": "C", "ts": ts,
                       "pid": pid, "args": args})
    norms = health_doc.get("norms", {})
    if _finite(norms.get("grad_norm")):
        events.append({"name": "mx_health_grad_norm", "ph": "C",
                       "ts": ts, "pid": pid,
                       "args": {"l2": norms["grad_norm"]}})
    sentry = health_doc.get("sentry", {})
    by_source = sentry.get("by_source") or {}
    events.append({
        "name": "mx_health_nonfinite_total", "ph": "C", "ts": ts,
        "pid": pid,
        "args": ({src: n for src, n in sorted(by_source.items())}
                 if by_source
                 else {"total": sentry.get("nonfinite_total", 0)})})
    return events


def chrome_events(spans, pid=0, offset_ns=0, base_ns=None):
    """Span dicts -> chrome-trace 'X' events. ``offset_ns`` is added to
    every timestamp (clock alignment); ``base_ns`` is the zero point
    (defaults to the process epoch so profiler events and spans share
    one axis)."""
    if base_ns is None:
        base_ns = clock.EPOCH_NS
    out = []
    for s in spans:
        args = {"trace": "%016x" % (s.get("trace") or 0),
                "span": "%016x" % (s.get("span") or 0)}
        if s.get("parent"):
            args["parent"] = "%016x" % s["parent"]
        if s.get("attrs"):
            args.update(s["attrs"])
        out.append({
            "name": s["name"], "cat": s.get("cat") or "span", "ph": "X",
            "ts": (s["start_ns"] + offset_ns - base_ns) / 1e3,
            "dur": s["dur_ns"] / 1e3,
            "pid": pid, "tid": s.get("tid", 0) % 100000,
            "args": args,
        })
    return out
