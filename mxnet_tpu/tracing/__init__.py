"""Causal tracing: spans with context propagation — the "why" half of
observability (telemetry/ answers "how much", profiler.py "when").

A :func:`span` is a named, monotonic-clocked interval with a parent
link, carried by a ``contextvars.ContextVar`` so nesting follows the
code even across the framework's seams: engine host-task push→exec
edges, ``DataIter.__next__``, executor forward/backward, checkpoint
save/restore, and the kvstore wire protocol (a worker push/pull span's
``(trace_id, span_id)`` rides the request header — see comm.cc wire v2
— and the server opens child spans for recv/update).

Closed spans land in bounded per-thread ring buffers; nothing is ever
written unless asked.  Consumers:

- ``tracing.export.write_trace(path)`` — one trace file per process,
  stitched across ranks by ``tools/trace_merge.py``;
- ``tracing.flight`` — the hang flight recorder: the same rings plus
  the per-thread *open* (in-flight) spans, dumped with thread stacks
  on SIGTERM, unhandled crash, or a watchdog timeout
  (``MXTPU_HANG_TIMEOUT_SEC``);
- ``telemetry`` — span durations of framework seams feed the
  ``mx_span_seconds`` histogram family.

Knobs: ``MXTPU_TRACE_SAMPLE`` (0..1 trace-level sampling, default 1 —
rings are cheap; 0 disables recording entirely), ``MXTPU_TRACE_RING``
(closed spans retained per thread, default 2048), ``MXTPU_TRACE_FILE``
(default export path). All jax-free: the module imports at interpreter
speed and works in the kvstore server process.
"""
from __future__ import annotations

import contextvars
import os
import random as _random_mod
import threading

from ..base import get_env
from . import clock

__all__ = ["span", "span_at", "traced", "record_span", "current",
           "context", "new_context", "enabled", "set_sample", "drain",
           "spans_snapshot", "reset", "clock", "flight", "export",
           "rings"]

_SAMPLE = [get_env("MXTPU_TRACE_SAMPLE", 1.0, float)]
_RING_CAP = max(int(get_env("MXTPU_TRACE_RING", 2048, int)), 16)

# span/trace ids draw from a PRIVATE rng: the global `random` module is
# user-visible state (MXNET_TEST_SEED determinism contract) and tracing
# must not perturb it
_rng = _random_mod.Random()

# current span, per OS thread (each thread owns a fresh Context)
_ctx = contextvars.ContextVar("mxtpu_trace_span", default=None)

# watchdog heartbeat: monotonic ns of the last span open/close anywhere
# in the process (a wedged process stops advancing this)
_last_activity = [clock.now_ns()]


def _touch():
    _last_activity[0] = clock.now_ns()


def last_activity_ns():
    return _last_activity[0]


def enabled():
    """Whether spans record (MXTPU_TRACE_SAMPLE > 0)."""
    return _SAMPLE[0] > 0.0


def set_sample(p):
    """Set the trace sampling probability at runtime (0 disables)."""
    _SAMPLE[0] = float(p)


def _new_id():
    return _rng.getrandbits(63) | 1   # nonzero: 0 means "untraced" on the wire


# -- per-thread rings --------------------------------------------------------
class _ThreadRing:
    """One thread's closed-span ring + open-span stack. Mutated only by
    its owner thread; readers (flight recorder, export) take the module
    lock and copy — a torn read of a plain list append is benign."""

    __slots__ = ("thread_name", "ident", "closed", "open", "alive")

    def __init__(self, thread):
        self.thread_name = thread.name
        self.ident = thread.ident
        self.closed = []          # bounded FIFO of span dicts
        self.open = []            # in-flight Span objects, LIFO
        self.alive = True


_rings_lock = threading.Lock()
_rings = []                       # every thread ring ever registered
_tls = threading.local()


def _ring():
    r = getattr(_tls, "ring", None)
    if r is None:
        r = _ThreadRing(threading.current_thread())
        _tls.ring = r
        with _rings_lock:
            # bound dead-ring retention: threads come and go (prefetch
            # workers, server connection threads); refresh liveness HERE
            # — registration is the only hook guaranteed to run under
            # thread churn (rings() only runs when a dump/export asks) —
            # then keep the most recent few dead rings for post-mortem
            alive_ids = {t.ident for t in threading.enumerate()}
            for x in _rings:
                x.alive = x.ident in alive_ids
            dead = [x for x in _rings if not x.alive and not x.open]
            for x in dead[:-16]:
                _rings.remove(x)
            _rings.append(r)
    return r


def rings():
    """[(thread_name, ident, closed_spans_copy, open_spans_copy)] for
    every registered thread (flight recorder / export substrate)."""
    # timed acquire, then a lock-free fallback: the flight recorder
    # calls this from a SIGTERM handler, which may have interrupted a
    # frame on THIS thread that already holds the (non-reentrant) lock
    # — blocking would deadlock the dying process and starve the
    # chained handler (e.g. PreemptionGuard's deferred checkpoint).
    # list(_rings) without the lock is a GIL-atomic copy; worst case a
    # torn view, which a dump tolerates by design.
    got = _rings_lock.acquire(timeout=0.5)
    try:
        rs = list(_rings)
    finally:
        if got:
            _rings_lock.release()
    alive = {t.ident for t in threading.enumerate()}
    out = []
    for r in rs:
        r.alive = r.ident in alive
        out.append((r.thread_name, r.ident, list(r.closed), list(r.open)))
    return out


# -- spans -------------------------------------------------------------------
class Span:
    """One in-flight interval. Use via ``with span(...)``; reading
    ``trace_id``/``span_id`` while open is how the kvstore worker puts
    the context on the wire."""

    __slots__ = ("name", "cat", "attrs", "trace_id", "span_id",
                 "parent_id", "start_ns", "_token", "_ring_ref")

    def __init__(self, name, cat, attrs, trace_id, parent_id):
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start_ns = 0
        self._token = None
        self._ring_ref = None

    def set_attr(self, key, value):
        self.attrs[key] = value

    def __enter__(self):
        self.start_ns = clock.now_ns()
        self._token = _ctx.set(self)
        r = self._ring_ref = _ring()
        r.open.append(self)
        _touch()
        return self

    def __exit__(self, exc_type, exc, tb):
        end_ns = clock.now_ns()
        _ctx.reset(self._token)
        r = self._ring_ref
        if r.open and r.open[-1] is self:
            r.open.pop()
        else:                      # out-of-order close (rare)
            try:
                r.open.remove(self)
            except ValueError:
                pass
        if exc_type is not None and exc_type is not StopIteration:
            self.attrs["error"] = exc_type.__name__
        rec = {"name": self.name, "cat": self.cat,
               "trace": self.trace_id, "span": self.span_id,
               "parent": self.parent_id,
               "start_ns": self.start_ns, "dur_ns": end_ns - self.start_ns,
               "tid": r.ident, "thread": r.thread_name,
               "attrs": self.attrs}
        r.closed.append(rec)
        if len(r.closed) > _RING_CAP:
            del r.closed[:-_RING_CAP]
        _touch()
        if self.cat is not None:
            _observe_span(self.name, (end_ns - self.start_ns) / 1e9)
        return False


class _NoopSpan:
    """Returned when tracing is disabled/unsampled: zero ids (untraced
    on the wire), records nothing."""

    __slots__ = ()
    trace_id = 0
    span_id = 0
    parent_id = 0
    name = cat = None
    attrs = {}

    def set_attr(self, key, value):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP = _NoopSpan()


class _UnsampledCtx:
    """Entered when a ROOT span loses the sampling roll: it occupies
    the contextvar with ``trace_id`` 0 so every descendant inherits the
    unsampled decision (returns NOOP) instead of re-rolling into an
    orphan parentless trace."""

    __slots__ = ("_token",)
    trace_id = 0
    span_id = 0
    parent_id = 0
    name = cat = None
    attrs = {}

    def set_attr(self, key, value):
        pass

    def __enter__(self):
        self._token = _ctx.set(self)
        _touch()
        return self

    def __exit__(self, *exc):
        _ctx.reset(self._token)
        _touch()
        return False


def span(name, cat=None, **attrs):
    """Open a traced interval::

        with tracing.span("load_batch", cat="io", step=7):
            ...

    Parent is the innermost open span on this thread (contextvar); a
    span with no parent starts a new trace and takes the sampling
    decision (``MXTPU_TRACE_SAMPLE``) for everything beneath it — an
    unsampled root still enters the context so its descendants inherit
    the decision rather than re-rolling.
    ``cat`` marks framework seams ("io", "comm", "compute", "engine",
    "checkpoint", "step") — spans with a cat feed ``mx_span_seconds``.
    """
    if _SAMPLE[0] <= 0.0:
        return NOOP
    parent = _ctx.get()
    if parent is not None:
        if parent.trace_id == 0:     # inside an unsampled trace
            return NOOP
        return Span(name, cat, attrs, parent.trace_id, parent.span_id)
    s = _SAMPLE[0]
    if s < 1.0 and _rng.random() >= s:
        return _UnsampledCtx()
    return Span(name, cat, attrs, _new_id(), None)


def span_at(ctx, name, cat=None, **attrs):
    """Open a span parented to a context captured on ANOTHER thread
    (``ctx`` is :func:`context`'s ``(trace_id, span_id)`` tuple) — the
    async edge: capture at push time, reopen on the worker thread."""
    if _SAMPLE[0] <= 0.0:
        return NOOP
    if not ctx or not ctx[0]:
        return span(name, cat=cat, **attrs)
    return Span(name, cat, attrs, ctx[0], ctx[1])


def current():
    """The innermost open Span on this thread, or None."""
    return _ctx.get()


def context():
    """``(trace_id, span_id)`` of the current span — the wire/cross-
    thread propagation token. ``(0, 0)`` when untraced."""
    cur = _ctx.get()
    if cur is None:
        return (0, 0)
    return (cur.trace_id, cur.span_id)


def new_context():
    """Mint a fresh ``(trace_id, 0)`` context for a root that will be
    recorded externally via :func:`record_span` — e.g. a serving
    request entering the gateway with no enclosing span still needs a
    trace id to carry through queue → batch → execute → reply.
    ``(0, 0)`` when tracing is disabled — and the fractional
    MXTPU_TRACE_SAMPLE roll applies exactly as it does to a root
    :func:`span` (record_span records unconditionally for a nonzero
    trace id, so skipping the dice here would trace 100%% of serving
    requests at a 1%% sampling setting)."""
    s = _SAMPLE[0]
    if s <= 0.0:
        return (0, 0)
    if s < 1.0 and _rng.random() >= s:
        return (0, 0)
    return (_new_id(), 0)


def traced(fn=None, name=None, cat=None):
    """Decorator form: ``@traced`` / ``@traced(name=..., cat=...)``."""
    import functools

    def deco(f):
        label = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with span(label, cat=cat):
                return f(*args, **kwargs)
        return wrapper
    return deco(fn) if fn is not None else deco


def record_span(name, trace_id, parent_id, start_ns, end_ns, cat=None,
                attrs=None):
    """Append an already-finished span (remote/native side, e.g. the
    kvstore server's recv intervals reported by the C++ trace sink).
    Returns the new span id."""
    if _SAMPLE[0] <= 0.0:
        return 0
    r = _ring()
    sid = _new_id()
    r.closed.append({"name": name, "cat": cat,
                     "trace": int(trace_id), "span": sid,
                     "parent": int(parent_id) or None,
                     "start_ns": int(start_ns),
                     "dur_ns": int(end_ns) - int(start_ns),
                     "tid": r.ident, "thread": r.thread_name,
                     "attrs": dict(attrs or {})})
    if len(r.closed) > _RING_CAP:
        del r.closed[:-_RING_CAP]
    _touch()
    return sid


# -- ring readout ------------------------------------------------------------
def spans_snapshot():
    """Copy of every closed span across all thread rings (oldest first
    per thread), non-destructive."""
    out = []
    for _, _, closed, _ in rings():
        out.extend(closed)
    out.sort(key=lambda s: s["start_ns"])
    return out


def drain():
    """Like :func:`spans_snapshot` but clears the rings (export path)."""
    with _rings_lock:
        rs = list(_rings)
    out = []
    for r in rs:
        closed, r.closed = r.closed, []
        out.extend(closed)
    out.sort(key=lambda s: s["start_ns"])
    return out


def reset():
    """Drop all recorded spans (test isolation). Open spans survive —
    they belong to live frames."""
    with _rings_lock:
        rs = list(_rings)
    for r in rs:
        r.closed = []


# -- telemetry feed ----------------------------------------------------------
# per-name series cache: one lock+observe per span close. Lazy import:
# telemetry.export lazily imports tracing for the chrome-trace merge,
# so a module-level import here would be circular on standalone loads.
_span_series = {}


def _observe_span(name, seconds):
    try:
        from ..telemetry import metrics as _tm
    except ImportError:        # standalone tracing load (tools/)
        return
    if not _tm.enabled():
        return
    s = _span_series.get(name)
    if s is None:
        fam = _tm.registry().histogram(
            "mx_span_seconds",
            "duration of framework-seam trace spans, by span name",
            labelnames=("name",))
        s = _span_series[name] = fam.labels(name=name)
    s.observe(seconds)


from . import flight  # noqa: E402  (imports tracing core above)
from . import export  # noqa: E402
