"""One monotonic clock for every timeline the process emits.

The profiler's chrome-trace events, the tracing spans, and the native
transport's server-side timestamps must live on a single time axis, or
a merged Perfetto artifact interleaves incompatible epochs (the PR 5
fix: profiler.py used its own ``perf_counter`` offset captured at its
import, spans would have used another — events recorded in the same
millisecond rendered minutes apart).

``EPOCH_NS`` is captured exactly once per process, at first import of
this module; everything that renders a relative timestamp subtracts it.
Absolute values are ``time.monotonic_ns()``: on Linux that is
CLOCK_MONOTONIC, the same clock C++'s ``steady_clock`` reads in
comm.cc, so worker-Python, server-Python and server-C++ timestamps on
one host are directly comparable. Across hosts (or artificially skewed
test traces) alignment is tools/trace_merge.py's job.
"""
from __future__ import annotations

import time

# process-wide monotonic epoch: captured ONCE, shared by profiler.py
# (chrome-trace ts) and tracing (span export) — never reassigned
EPOCH_NS = time.monotonic_ns()


def now_ns():
    """Current CLOCK_MONOTONIC time in nanoseconds (absolute)."""
    return time.monotonic_ns()


def rel_us(ns):
    """Absolute monotonic ns -> microseconds since the process epoch
    (the chrome-trace ``ts`` unit)."""
    return (ns - EPOCH_NS) / 1e3
