"""NDArray — async device array on top of jax.Array.

TPU-native analogue of include/mxnet/ndarray.h + python/mxnet/ndarray/ndarray.py.
Where the reference's NDArray holds a Storage chunk plus an engine variable and
every op is pushed to the threaded engine, this NDArray holds a jax.Array whose
PJRT buffer is *already* asynchronous: dispatch returns immediately, per-device
execution is stream-ordered, and `wait_to_read` maps to block_until_ready
(deferred errors surface there — the reference's rethrow-at-WaitForVar
contract, src/engine/threaded_engine.cc:472-479). The MKL-DNN opaque-layout
seam (ndarray.cc:389-744 Reorder2Default) corresponds to the device-resident
tiled layout PJRT keeps; `asnumpy()` is the explicit relayout boundary.
"""
from __future__ import annotations

import numbers

import jax
import jax.numpy as jnp
import numpy as np

from .. import engine
from .. import random as _random
from ..base import MXNetError
from ..context import Context, ctx_from_jax_device, current_context
from ..ops import registry as _reg

__all__ = ["NDArray", "array", "invoke", "concatenate"]

_DTYPE_ALIAS = {None: jnp.float32}


def _materialize(data, dtype=None):
    """asarray that yields a *concrete* jax.Array for concrete input even
    when called inside an ambient trace (jit / eval_shape); tracers pass
    through as ordinary traced asarray.

    Deferred parameter init can fire while a Gluon forward is being traced
    for shape inference; without this escape the freshly created constant
    would be a tracer of that trace, leak into ``Parameter._data``, and blow
    up at the next real use (UnexpectedTracerError — the round-2 bench
    failure). ``ensure_compile_time_eval`` runs the creation outside the
    trace, so parameters/gradients are always real device arrays."""
    if isinstance(data, jax.core.Tracer):
        return jnp.asarray(data, dtype=dtype)
    with jax.ensure_compile_time_eval():
        return jnp.asarray(data, dtype=dtype)


def _canon_attr(v):
    """Normalize attr values: lists -> tuples (hashable for jit static args),
    numpy scalars -> python scalars, MXNet string tuples '(1, 1)' -> tuples."""
    if isinstance(v, list):
        return tuple(_canon_attr(x) for x in v)
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, str) and v.startswith("(") and v.endswith(")"):
        try:
            return tuple(int(x) for x in v[1:-1].split(",") if x.strip())
        except ValueError:
            return v
    return v


_INT32_MAX = 2 ** 31 - 1


def check_large_array(shape, num_shards=1):
    """Large-array policy (ref: tests/nightly/test_large_array.py — the
    reference supports >2^32-element NDArrays through int64 indexing).
    This runtime is x32 by default (jax's default; TPU gathers/indexing
    run int32), so element counts beyond 2^31-1 would silently corrupt
    take/Embedding/argmax results. Refuse at construction with the
    workaround spelled out rather than compute wrong numbers. With
    jax_enable_x64 the gate lifts; for sharded arrays the gate applies
    PER DEVICE SHARD (indexing is shard-local under SPMD), so the
    sharding workaround the error recommends actually works."""
    n = 1
    for d in shape:
        n *= int(d)
    if n // max(int(num_shards), 1) > _INT32_MAX \
            and not jax.config.jax_enable_x64:
        raise MXNetError(
            f"NDArray of {n} elements exceeds the 32-bit index range "
            f"({_INT32_MAX}) of the x32 runtime; indexing ops (take, "
            "Embedding, argmax) would overflow. Enable "
            "jax.config.update('jax_enable_x64', True) on a CPU host, "
            "or shard the array across devices with mxnet_tpu.parallel "
            "(the TPU-native answer at this scale)")
    return n


class NDArray:
    """n-dimensional device array with async semantics."""

    __slots__ = ("_data", "grad", "_grad_req", "_entry", "__weakref__")

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            # gate BEFORE materialization: the refusal must beat the
            # host->device transfer, not follow a device OOM
            if hasattr(data, "shape"):
                check_large_array(data.shape)
            data = _materialize(data)
        sharding = getattr(data, "sharding", None)
        if sharding is not None:
            # the true shard factor, not the device count: a replicated
            # array on 8 devices still holds ALL elements per device
            try:
                shard_elems = 1
                for d in sharding.shard_shape(tuple(data.shape)):
                    shard_elems *= int(d)
                total = 1
                for d in data.shape:
                    total *= int(d)
                n_shards = max(total // max(shard_elems, 1), 1)
            except Exception:  # noqa: BLE001 — odd sharding type
                n_shards = 1
            check_large_array(data.shape, num_shards=n_shards)
        else:
            check_large_array(data.shape)
        if ctx is not None:
            data = jax.device_put(data, Context(ctx).jax_device)
        self._data = data
        self.grad = None
        self._grad_req = "null"
        self._entry = None

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(str(self._data.dtype))

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            return current_context()
        return ctx_from_jax_device(dev)

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def handle(self):
        return self._data

    # -- sync / conversion -------------------------------------------------
    def asnumpy(self):
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        jax.block_until_ready(self._data)

    def wait_to_write(self):
        jax.block_until_ready(self._data)

    def as_in_context(self, ctx):
        ctx = Context(ctx)
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device))

    as_in_ctx = as_in_context

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, other.context.jax_device)
            return other
        return NDArray(self._data, ctx=other)

    def copy(self):
        return NDArray(jnp.copy(self._data))

    def astype(self, dtype, copy=True):
        return invoke("Cast", [self], {"dtype": np.dtype(dtype).name})

    def tostype(self, stype):
        if stype != "default":
            from . import sparse as _sp
            return _sp.cast_storage(self, stype)
        return self

    def asnetype(self):
        return self

    def detach(self):
        out = NDArray(self._data)
        return out

    # -- autograd ----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        from .. import autograd
        from ..profiling import memory as _mem
        self.grad = NDArray(_materialize(
            np.zeros(self._data.shape, self._data.dtype)
            if not isinstance(self._data, jax.core.Tracer)
            else jnp.zeros_like(self._data)))
        # census role for the memory attribution layer (a weakref
        # side-table write, no device work; profiling/memory.py)
        _mem.tag_role(self.grad, "gradient")
        self._grad_req = grad_req
        autograd._mark_variable(self)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- python protocol ---------------------------------------------------
    def __repr__(self):
        return f"\n{self.asnumpy()!r}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        return int(self.asscalar())

    def __hash__(self):
        return id(self)

    def __getstate__(self):
        return {"data": self.asnumpy(), "grad_req": self._grad_req}

    def __setstate__(self, state):
        self._data = jnp.asarray(state["data"])
        self.grad = None
        self._grad_req = state.get("grad_req", "null")
        self._entry = None

    # -- indexing ----------------------------------------------------------
    def _key(self, key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(k._data if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        from .. import autograd
        key = self._key(key)
        if autograd.is_recording() and self._entry is not None:
            return autograd._record_getitem(self, key)
        out = self._data[key]
        return NDArray(out)

    @staticmethod
    def _setitem_slices(key, ndim):
        """Normalize a basic-slicing key to (begin, end, step) tuples for
        _slice_assign; None when the key needs advanced indexing."""
        key = key if isinstance(key, tuple) else (key,)
        if any(k is Ellipsis for k in key):
            i = key.index(Ellipsis)
            key = key[:i] + (slice(None),) * (ndim - len(key) + 1) \
                + key[i + 1:]
        begin, end, step = [], [], []
        for k in key:
            if isinstance(k, slice):
                begin.append(k.start)
                end.append(k.stop)
                step.append(k.step)
            elif isinstance(k, numbers.Integral):
                b = int(k)
                begin.append(b)
                end.append(None if b == -1 else b + 1)
                step.append(None)
            else:
                return None
        for _ in range(ndim - len(begin)):
            begin.append(None)
            end.append(None)
            step.append(None)
        return tuple(begin), tuple(end), tuple(step)

    def __setitem__(self, key, value):
        from .. import autograd
        if autograd.is_recording() and self._entry is not None:
            # recorded in-place assignment lowers to the functional
            # _slice_assign op (ref: tensor/matrix_op.cc _slice_assign —
            # the same rewrite the reference's autograd performs); self
            # rebinds to the op output so the tape sees a fresh array
            spec = self._setitem_slices(key, self._data.ndim)
            if spec is None:
                raise MXNetError(
                    "recorded in-place assignment supports only basic "
                    "slicing; use masked ops (where/boolean_mask_fill) "
                    "for advanced indexing")
            begin, end, step = spec
            attrs = {"begin": begin, "end": end, "step": step}
            if not isinstance(value, NDArray) and \
                    not isinstance(value, numbers.Number):
                value = NDArray(jnp.asarray(value))  # list / np.ndarray
            if isinstance(value, NDArray):
                out = invoke("_slice_assign", [self, value], attrs)
            else:
                attrs["scalar"] = float(value)
                out = invoke("_slice_assign_scalar", [self], attrs)
            self._data = out._data
            self._entry = out._entry
            return
        key = self._key(key)
        if isinstance(value, NDArray):
            value = value._data
        self._data = self._data.at[key].set(value)

    # -- arithmetic --------------------------------------------------------
    def _binary(self, other, op, scalar_op, rev=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if rev else (self, other)
            return invoke(op, [a, b], {})
        if isinstance(other, numbers.Number):
            return invoke(scalar_op, [self], {"scalar": float(other)})
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "_rminus_scalar", rev=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", "_rdiv_scalar", rev=True)

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._binary(o, "broadcast_mod", "_rmod_scalar", rev=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binary(o, "broadcast_power", "_rpower_scalar", rev=True)

    def __neg__(self):
        return invoke("negative", [self], {})

    def __abs__(self):
        return invoke("abs", [self], {})

    def __matmul__(self, o):
        return invoke("dot", [self, o], {})

    def __eq__(self, o):
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def _inplace(self, res):
        self._data = res._data
        self._entry = res._entry
        return self

    def __iadd__(self, o):
        return self._inplace(self + o)

    def __isub__(self, o):
        return self._inplace(self - o)

    def __imul__(self, o):
        return self._inplace(self * o)

    def __itruediv__(self, o):
        return self._inplace(self / o)

    # -- method forms of common ops ---------------------------------------
    @property
    def T(self):
        return invoke("transpose", [self], {})

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return invoke("Reshape", [self], {"shape": tuple(shape),
                                          "reverse": kwargs.get("reverse", False)})

    def reshape_like(self, other):
        return invoke("Reshape", [self], {"shape": other.shape})


def _arrayish(v):
    """Array-valued argument (numpy/jax) that should become an input,
    not an attr — mirrors register.py's _is_tensor classification."""
    return isinstance(v, np.ndarray) or (
        hasattr(v, "shape") and hasattr(v, "dtype") and not np.isscalar(v))


def _make_method(opname):
    def method(self, *args, **kwargs):
        attrs = {k: _canon_attr(v) for k, v in kwargs.items() if v is not None}
        extra = []
        scalars = []
        for a in args:
            if isinstance(a, NDArray):
                extra.append(a)
            elif _arrayish(a):
                extra.append(NDArray(jnp.asarray(a)))
            else:
                scalars.append(a)
        if scalars:
            # bind positional non-array args (x.transpose(0, 2, 1),
            # x.clip(0, 1), x.sum(0)...) to the op's declared attr names
            # in signature order — silently dropping them produced
            # reversed transposes (round-4 capsnet finding)
            _reg.bind_positional_attrs(_reg.get(opname), scalars, attrs,
                                       err_cls=MXNetError)
        return invoke(opname, [self] + extra, attrs)

    method.__name__ = opname
    return method


for _m in ["abs", "sign", "square", "sqrt", "rsqrt", "exp", "log", "log2",
           "log10", "log1p", "sin", "cos", "tan", "tanh", "sigmoid", "relu",
           "sum", "mean", "prod", "max", "min", "norm", "argmax", "argmin",
           "flatten", "transpose", "expand_dims", "squeeze", "flip", "tile",
           "repeat", "clip", "take", "pick", "one_hot", "topk", "sort",
           "argsort", "zeros_like", "ones_like", "swapaxes", "slice_axis",
           "slice_like", "broadcast_to", "broadcast_like", "diag",
           "softmax", "log_softmax"]:
    if not hasattr(NDArray, _m):
        setattr(NDArray, _m, _make_method(_m))

NDArray.split = _make_method("SliceChannel")
NDArray.pad = _make_method("Pad")
NDArray.dot = _make_method("dot")
NDArray.batch_dot = _make_method("batch_dot")


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def invoke(op_name, inputs, attrs, out=None):
    """Imperative op dispatch (MXImperativeInvoke analogue,
    ref: src/imperative/imperative.cc:87). Unwraps NDArrays, injects RNG keys
    and the autograd train-mode flag, executes on the PJRT stream, records on
    the tape when autograd is active."""
    from .. import autograd

    op = _reg.get(op_name) if isinstance(op_name, str) else op_name
    attrs = {k: _canon_attr(v) for k, v in attrs.items() if v is not None}
    if "num_args" not in op._kwarg_names:
        # the input count is implied by the arrays, but the reference's
        # generated API still passes num_args — accept and drop, except for
        # ops that genuinely consume it (e.g. UpSampling's concat gate)
        attrs.pop("num_args", None)
    if "training" in op._kwarg_names and "training" not in attrs:
        attrs["training"] = autograd.is_training()

    arrays = []
    consts = []
    if op.needs_rng:
        key = _random.next_key()
        arrays.append(key)
        consts.append(key)
    nd_inputs = []
    for i in inputs:
        if i is None:
            continue
        if not isinstance(i, NDArray):
            i = NDArray(i)
        nd_inputs.append(i)
        arrays.append(i._data)

    from .. import profiler
    if profiler.is_running():
        # engine-style per-op stamp (ref: threaded_engine.cc:481 stops
        # the ProfileOperator timer at completion); dispatch is async so
        # this times submission — the XLA-side kernel timeline comes
        # from profiler.set_config(xla_trace_dir=...)
        with profiler.timed_operator(op.name):
            raw = op(*arrays, **attrs)
    else:
        raw = op(*arrays, **attrs)
    multi = isinstance(raw, (tuple, list))
    raws = list(raw) if multi else [raw]
    outs = [NDArray(r) for r in raws]

    if autograd.is_recording():
        autograd._record_op(op, attrs, nd_inputs, outs, rng_consts=consts)

    engine.on_op_executed(raws)

    if out is not None:
        targets = out if isinstance(out, (tuple, list)) else [out]
        for t, o in zip(targets, outs):
            t._data = o._data
            t._entry = o._entry
        return out
    return tuple(outs) if multi else outs[0]


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (ref: ndarray.py array())."""
    if isinstance(source_array, NDArray):
        data = source_array._data
    elif isinstance(source_array, np.ndarray):
        data = source_array if dtype is None else source_array.astype(dtype)
        if dtype is None and data.dtype == np.float64:
            data = data.astype(np.float32)
        if dtype is None and data.dtype == np.int64:
            data = data.astype(np.int32)
    else:
        # python lists default to float32, like the reference
        data = np.asarray(source_array, dtype=dtype or np.float32)
    out = NDArray(_materialize(data, dtype=dtype and np.dtype(dtype)))
    if ctx is not None:
        out = out.as_in_context(ctx)
    return out


def concatenate(arrays, axis=0, always_copy=True):
    return invoke("Concat", list(arrays), {"dim": axis})
