"""NDArray save/load (ref: python/mxnet/ndarray/utils.py + ndarray.cc Save/Load).

Container format: numpy .npz with a key-order manifest. Not byte-compatible
with the reference's dmlc binary format, but the API contract (list or
str->NDArray dict round trip, used by save_checkpoint / load_parameters) is
preserved.
"""
from __future__ import annotations

import numpy as np

from .ndarray import NDArray, array

_LIST_PREFIX = "__list__:"


def save(fname, data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        payload = {f"{_LIST_PREFIX}{i}": d.asnumpy() for i, d in enumerate(data)}
    elif isinstance(data, dict):
        payload = {k: v.asnumpy() for k, v in data.items()}
    else:
        raise TypeError("save expects NDArray, list or dict of NDArrays")
    with open(fname, "wb") as f:
        np.savez(f, **payload)


def _from_npz(npz):
    keys = list(npz.keys())
    if keys and all(k.startswith(_LIST_PREFIX) for k in keys):
        keys.sort(key=lambda k: int(k[len(_LIST_PREFIX):]))
        return [array(npz[k]) for k in keys]
    return {k: array(npz[k]) for k in keys}


def load(fname):
    with open(fname, "rb") as f:
        head = f.read(8)
    from .ref_serde import is_reference_format
    if is_reference_format(head):
        # reference-format .params checkpoints load transparently
        with open(fname, "rb") as f:
            return load_frombuffer(f.read())
    with np.load(fname, allow_pickle=False) as npz:
        return _from_npz(npz)


def load_frombuffer(buf):
    """Deserialize an NDArray dict/list from in-memory bytes (ref:
    python/mxnet/ndarray/utils.py load_frombuffer / MXNDArrayLoad
    FromBuffer). Accepts both this framework's npz container and the
    reference's dmlc byte format (ndarray/ref_serde.py)."""
    import io as _io

    from .ref_serde import is_reference_format, load_reference_buffer
    if is_reference_format(buf):
        return {k: array(v) for k, v in load_reference_buffer(buf).items()}
    with np.load(_io.BytesIO(buf), allow_pickle=False) as npz:
        return _from_npz(npz)
