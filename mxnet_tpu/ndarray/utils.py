"""NDArray save/load (ref: python/mxnet/ndarray/utils.py + ndarray.cc Save/Load).

Container format: numpy .npz with a key-order manifest. Not byte-compatible
with the reference's dmlc binary format, but the API contract (list or
str->NDArray dict round trip, used by save_checkpoint / load_parameters) is
preserved. Writes are preemption-safe: every file goes through
checkpoint.atomic_write (tmp + fsync + rename, CRC32 recorded in the
directory's MANIFEST.json), and load() CRC-verifies against that manifest
before deserializing — a torn or bit-flipped checkpoint raises MXNetError
instead of loading as wrong weights (docs/robustness.md).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .ndarray import NDArray, array

_LIST_PREFIX = "__list__:"


def _coerce(key, value):
    """NDArray/numpy -> numpy payload; anything else is a clear
    TypeError (the reference raised a bare AttributeError from
    v.asnumpy() on plain numpy inputs)."""
    if isinstance(value, NDArray):
        return value.asnumpy()
    if isinstance(value, np.ndarray):
        return value
    raise TypeError(
        f"nd.save: value for {key!r} must be an NDArray or numpy "
        f"ndarray, got {type(value).__name__}")


def save(fname, data):
    from .. import checkpoint as ckpt
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        payload = {f"{_LIST_PREFIX}{i}": _coerce(i, d)
                   for i, d in enumerate(data)}
    elif isinstance(data, dict):
        payload = {k: _coerce(k, v) for k, v in data.items()}
    else:
        raise TypeError("save expects NDArray, list or dict of NDArrays")
    with ckpt.atomic_write(fname) as f:
        np.savez(f, **payload)


def _from_npz(npz):
    keys = list(npz.keys())
    if keys and all(k.startswith(_LIST_PREFIX) for k in keys):
        keys.sort(key=lambda k: int(k[len(_LIST_PREFIX):]))
        return [array(npz[k]) for k in keys]
    return {k: array(npz[k]) for k in keys}


def _decode_error(name, head, exc):
    """One MXNetError naming the file and the probable cause: a
    recognizable container that failed to decode is a torn write; an
    unrecognizable header is the wrong format."""
    from .ref_serde import is_reference_format
    if head[:2] == b"PK" or is_reference_format(head):
        cause = ("probable torn/truncated write — the container header "
                 "is valid but its contents do not decode")
    else:
        cause = ("not a recognized NDArray container (npz or reference "
                 ".params) — wrong format or completely garbled")
    return MXNetError(f"failed to load NDArray file {name}: {cause} "
                      f"[{type(exc).__name__}: {exc}]")


def load(fname):
    from .. import checkpoint as ckpt
    from .ref_serde import is_reference_format

    # CRC gate first: a manifest-listed file with ANY flipped or missing
    # byte is rejected here, before any decoder can mis-read it
    ckpt.verify(fname)
    with open(fname, "rb") as f:
        head = f.read(8)
    if is_reference_format(head):
        # reference-format .params checkpoints load transparently
        with open(fname, "rb") as f:
            buf = f.read()
        try:
            from .ref_serde import load_reference_buffer
            return {k: array(v)
                    for k, v in load_reference_buffer(buf).items()}
        except Exception as e:  # noqa: BLE001 — surface one clean error
            raise _decode_error(fname, head, e) from e
    try:
        with np.load(fname, allow_pickle=False) as npz:
            return _from_npz(npz)
    except Exception as e:  # noqa: BLE001 — BadZipFile/ValueError/...
        raise _decode_error(fname, head, e) from e


def load_frombuffer(buf):
    """Deserialize an NDArray dict/list from in-memory bytes (ref:
    python/mxnet/ndarray/utils.py load_frombuffer / MXNDArrayLoad
    FromBuffer). Accepts both this framework's npz container and the
    reference's dmlc byte format (ndarray/ref_serde.py)."""
    import io as _io

    from .ref_serde import is_reference_format, load_reference_buffer
    if is_reference_format(buf):
        try:
            return {k: array(v)
                    for k, v in load_reference_buffer(buf).items()}
        except Exception as e:  # noqa: BLE001
            raise _decode_error("<buffer>", bytes(buf[:8]), e) from e
    try:
        with np.load(_io.BytesIO(buf), allow_pickle=False) as npz:
            return _from_npz(npz)
    except Exception as e:  # noqa: BLE001
        raise _decode_error("<buffer>", bytes(buf[:8]), e) from e
