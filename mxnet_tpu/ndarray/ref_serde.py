"""Reference-format NDArray-list (de)serialization.

The reference writes parameter files with dmlc streams (ref:
src/ndarray/ndarray.cc:1574 NDArray::Save and :1776 list save): u64
magic 0x112 | u64 reserved | vector<NDArray> | vector<string> keys.
Each dense NDArray is u32 magic 0xF993FAC9 | i32 stype | TShape (u32
ndim + u32 dims) | Context (i32, i32) | i32 mshadow type flag | raw
data; sparse entries carry storage shape and aux (indices) arrays.
These byte-level readers/writers make reference checkpoints a wire
format this framework speaks natively (nd.load_frombuffer,
tools/import_params.py, the MXPred C ABI's param blobs).
"""
from __future__ import annotations

import struct

import numpy as np

from ..base import MXNetError

LIST_MAGIC = 0x112
ND_MAGIC_V2 = 0xF993FAC9
ND_MAGIC_V1 = 0xF993FAC8

# mshadow type flags (ref: mshadow/base.h TypeFlag)
TYPE_FLAGS = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
              4: np.int32, 5: np.int8, 6: np.int64}
FLAG_OF = {np.dtype(v): k for k, v in TYPE_FLAGS.items()}

_STYPE_DEFAULT, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2


class _Reader:
    def __init__(self, data):
        self.b = data
        self.o = 0

    def read(self, fmt):
        vals = struct.unpack_from("<" + fmt, self.b, self.o)
        self.o += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def raw(self, n):
        out = self.b[self.o:self.o + n]
        self.o += n
        return out


def _read_shape(r):
    ndim = r.read("I")
    if ndim > 32:
        raise MXNetError(f"implausible ndim {ndim}: not a TShape")
    return tuple(r.read("I") for _ in range(ndim)) if ndim else ()


def _read_ndarray(r):
    magic = r.read("I")
    if magic == ND_MAGIC_V1:
        # legacy dense: shape | context | type_flag | data
        shape = _read_shape(r)
        r.read("ii")  # context
        flag = r.read("i")
        dt = np.dtype(TYPE_FLAGS[flag])
        n = int(np.prod(shape)) if shape else 0
        return np.frombuffer(r.raw(n * dt.itemsize), dt).reshape(shape)
    if magic != ND_MAGIC_V2:
        raise MXNetError(f"bad NDArray magic {magic:#x}")
    stype = r.read("i")
    nad = {_STYPE_DEFAULT: 0, _STYPE_ROW_SPARSE: 1, _STYPE_CSR: 2}[stype]
    sshape = _read_shape(r) if nad else None
    shape = _read_shape(r)
    if not shape:
        return np.zeros((0,), np.float32)
    r.read("ii")  # context dev_type/dev_id
    flag = r.read("i")
    dt = np.dtype(TYPE_FLAGS[flag])
    aux = []
    for _ in range(nad):
        aflag = r.read("i")
        ashape = _read_shape(r)
        aux.append((np.dtype(TYPE_FLAGS[aflag]), ashape))
    data_shape = sshape if nad else shape
    n = int(np.prod(data_shape)) if data_shape else 0
    values = np.frombuffer(r.raw(n * dt.itemsize), dt).reshape(data_shape)
    aux_arrays = []
    for adt, ashape in aux:
        an = int(np.prod(ashape)) if ashape else 0
        aux_arrays.append(
            np.frombuffer(r.raw(an * adt.itemsize), adt).reshape(ashape))
    if stype == _STYPE_ROW_SPARSE:
        dense = np.zeros(shape, dt)
        dense[aux_arrays[0].astype(np.int64)] = values
        return dense
    if stype == _STYPE_CSR:
        dense = np.zeros(shape, dt)
        indptr = aux_arrays[0].astype(np.int64)
        indices = aux_arrays[1].astype(np.int64)
        for row in range(shape[0]):
            cols = indices[indptr[row]:indptr[row + 1]]
            dense[row, cols] = values[indptr[row]:indptr[row + 1]]
        return dense
    return values


def is_reference_format(data):
    return len(data) >= 8 and \
        struct.unpack_from("<Q", data, 0)[0] == LIST_MAGIC


def load_reference_buffer(data):
    """Reference .params bytes -> {name: np.ndarray} ('arg:'/'aux:'
    prefixes preserved; Gluon-style files have bare names)."""
    r = _Reader(data)
    header, _reserved = r.read("QQ")
    if header != LIST_MAGIC:
        raise MXNetError(
            f"not a reference .params buffer (magic {header:#x})")
    count = r.read("Q")
    arrays = [_read_ndarray(r) for _ in range(count)]
    nkeys = r.read("Q")
    names = []
    for _ in range(nkeys):
        ln = r.read("Q")
        names.append(r.raw(ln).decode())
    if names and len(names) != len(arrays):
        raise MXNetError("corrupt buffer: key/array count mismatch")
    if not names:
        names = [f"ndarray_{i}" for i in range(len(arrays))]
    return dict(zip(names, arrays))


def save_reference_buffer(params):
    """{name: np.ndarray} -> reference dense .params bytes."""
    out = [struct.pack("<QQ", LIST_MAGIC, 0),
           struct.pack("<Q", len(params))]
    for arr in params.values():
        arr = np.ascontiguousarray(arr)
        flag = FLAG_OF.get(arr.dtype)
        if flag is None:
            arr = arr.astype(np.float32)
            flag = 0
        out.append(struct.pack("<Ii", ND_MAGIC_V2, _STYPE_DEFAULT))
        out.append(struct.pack("<I", arr.ndim))
        out.append(struct.pack(f"<{arr.ndim}I", *arr.shape))
        out.append(struct.pack("<ii", 1, 0))  # cpu:0
        out.append(struct.pack("<i", flag))
        out.append(arr.tobytes())
    out.append(struct.pack("<Q", len(params)))
    for name in params:
        enc = name.encode()
        out.append(struct.pack("<Q", len(enc)))
        out.append(enc)
    return b"".join(out)
