"""Sparse NDArray: row_sparse + CSR (ref: python/mxnet/ndarray/sparse.py,
include/mxnet/ndarray.h:61-82).

TPU has no native sparse compute (SURVEY.md §7 hard part (d)); storage is kept
genuinely sparse on host/HBM (indices + values), while compute lowers to
gather/scatter + dense MXU ops with static bounds. The KVStore row_sparse
push/pull path consumes these directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .ndarray import NDArray, array, invoke

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage", "zeros", "dot", "elemwise_add", "sparse_retain"]


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray:
    """data: (nnz_rows, *row_shape); indices: (nnz_rows,) sorted unique."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else array(data)
        if not isinstance(indices, NDArray):
            indices = array(np.asarray(indices, np.int64)
                            .astype(np.int32))
        if indices._data.dtype not in (jnp.int32, jnp.int64):
            indices = NDArray(indices._data.astype(jnp.int32))
        self.indices = indices
        self._shape = tuple(shape)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype != "default":
            raise MXNetError(f"cannot cast row_sparse to {stype}")
        dense = jnp.zeros(self._shape, dtype=self.data._data.dtype)
        dense = dense.at[self.indices._data].set(self.data._data)
        return NDArray(dense)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other.data = self.data.copy()
            other.indices = self.indices.copy()
            return other
        return self.tostype("default").copyto(other)

    def wait_to_read(self):
        self.data.wait_to_read()

    def __repr__(self):
        return (f"\n<RowSparseNDArray {self._shape} nnz_rows="
                f"{self.indices.shape[0]}>")

    def retain(self, indices):
        """Keep only the requested rows (ref: sparse_retain op)."""
        want = indices._data.astype(jnp.int32) if isinstance(indices, NDArray) \
            else jnp.asarray(indices, jnp.int32)
        have = self.indices._data
        # positions of `want` rows inside stored rows (missing -> zero row)
        eq = want[:, None] == have[None, :]
        pos = jnp.argmax(eq, axis=1)
        found = jnp.any(eq, axis=1)
        rows = self.data._data[pos]
        rows = jnp.where(found.reshape((-1,) + (1,) * (rows.ndim - 1)), rows, 0)
        return RowSparseNDArray(NDArray(rows), NDArray(want), self._shape)


class CSRNDArray:
    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self.data = data if isinstance(data, NDArray) else array(data)
        self.indices = (indices if isinstance(indices, NDArray)
                        else array(np.asarray(indices, dtype=np.int64).astype(np.int32)))
        self.indptr = (indptr if isinstance(indptr, NDArray)
                       else array(np.asarray(indptr, dtype=np.int64).astype(np.int32)))
        self._shape = tuple(shape)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype != "default":
            raise MXNetError(f"cannot cast csr to {stype}")
        m, n = self._shape
        nnz = self.data.shape[0]
        indptr = self.indptr._data
        rows = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        dense = jnp.zeros((m, n), dtype=self.data._data.dtype)
        dense = dense.at[rows, self.indices._data].set(self.data._data)
        return NDArray(dense)

    def copyto(self, other):
        return self.tostype("default").copyto(other)

    def wait_to_read(self):
        self.data.wait_to_read()

    def __repr__(self):
        return f"\n<CSRNDArray {self._shape} nnz={self.data.shape[0]}>"


def row_sparse_array(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        if not isinstance(indices, NDArray):
            indices = array(np.asarray(indices, dtype=np.int64).astype(np.int32))
        return RowSparseNDArray(array(data, dtype=dtype), indices, shape)
    dense = np.asarray(arg.asnumpy() if isinstance(arg, NDArray) else arg,
                       dtype=dtype or "float32")
    nz = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(array(dense[nz]), array(nz.astype(np.int32)),
                            dense.shape)


def csr_matrix(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        if not isinstance(indices, NDArray):
            indices = array(np.asarray(indices, dtype=np.int64).astype(np.int32))
        if not isinstance(indptr, NDArray):
            indptr = array(np.asarray(indptr, dtype=np.int64).astype(np.int32))
        return CSRNDArray(array(data, dtype=dtype), indices, indptr, shape)
    dense = np.asarray(arg.asnumpy() if isinstance(arg, NDArray) else arg,
                       dtype=dtype or "float32")
    m, n = dense.shape
    indptr = [0]
    indices, data = [], []
    for r in range(m):
        cols = np.where(dense[r] != 0)[0]
        indices.extend(cols.tolist())
        data.extend(dense[r, cols].tolist())
        indptr.append(len(indices))
    return CSRNDArray(array(np.asarray(data, dtype=dense.dtype)),
                      array(np.asarray(indices, dtype=np.int32)),
                      array(np.asarray(indptr, dtype=np.int32)), (m, n))


def cast_storage(arr, stype):
    """dense <-> sparse conversion (ref: src/operator/tensor/cast_storage.cc)."""
    if stype == "default":
        return arr.tostype("default") if not isinstance(arr, NDArray) else arr
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise MXNetError(f"unknown storage type {stype}")


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "row_sparse":
        width = shape[1:]
        return RowSparseNDArray(array(np.zeros((0,) + tuple(width))),
                                array(np.zeros((0,), np.int32)), shape)
    if stype == "csr":
        return CSRNDArray(array(np.zeros((0,))), array(np.zeros((0,), np.int32)),
                          array(np.zeros((shape[0] + 1,), np.int32)), shape)
    from . import zeros as dzeros
    return dzeros(shape, ctx=ctx, dtype=dtype)


def _csr_rows(csr):
    """Per-nnz row index from indptr (static nnz)."""
    nnz = csr.data.shape[0]
    return jnp.searchsorted(csr.indptr._data, jnp.arange(nnz),
                            side="right") - 1


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """csr·dense / rsp·dense dot without densifying the sparse side
    (ref: src/operator/tensor/dot-inl.h DotCsrDnsDns/DotCsrDnsRsp).

    CSR·dense is a gather + segment-sum over nnz — static shapes, so
    XLA compiles it once; the MXU sees only the dense gather/matmul.
    """
    rhs_nd = rhs if isinstance(rhs, NDArray) else NDArray(rhs)
    if isinstance(lhs, CSRNDArray):
        m, n = lhs.shape
        rows = _csr_rows(lhs)
        cols = lhs.indices._data
        vals = lhs.data._data
        b = rhs_nd._data
        if transpose_b:
            b = b.T
        if transpose_a:
            # out[n, k] = sum_nnz val * B[row]  grouped by col
            contrib = vals[:, None] * b[rows]
            out = jax.ops.segment_sum(contrib, cols, num_segments=n)
        else:
            # out[m, k] = sum_nnz val * B[col]  grouped by row
            contrib = vals[:, None] * b[cols]
            out = jax.ops.segment_sum(contrib, rows, num_segments=m)
        return NDArray(out)
    if isinstance(lhs, RowSparseNDArray):
        m = lhs.shape[0]
        b = rhs_nd._data
        if transpose_b:
            b = b.T
        if transpose_a:
            # out = A.T @ B: scatter-free — only stored rows contribute
            out = jnp.einsum("rd,rk->dk", 0 + lhs.data._data,
                             b[lhs.indices._data])
            return NDArray(out)
        rows_out = lhs.data._data @ b
        out = jnp.zeros((m, rows_out.shape[1]), rows_out.dtype)
        out = out.at[lhs.indices._data].set(rows_out)
        return NDArray(out)
    return invoke("dot", [lhs, rhs],
                  {"transpose_a": transpose_a, "transpose_b": transpose_b})


def elemwise_add(lhs, rhs):
    """sparse+sparse keeps row_sparse when row sets align; otherwise
    falls back to dense (TPU static shapes — a dynamic row-union would
    force recompiles, SURVEY.md §7 hard part (d))."""
    if isinstance(lhs, RowSparseNDArray) and             isinstance(rhs, RowSparseNDArray):
        if lhs.indices.shape == rhs.indices.shape and bool(
                jnp.all(lhs.indices._data == rhs.indices._data)):
            return RowSparseNDArray(
                NDArray(lhs.data._data + rhs.data._data),
                lhs.indices, lhs.shape)
        return NDArray(lhs.tostype("default")._data +
                       rhs.tostype("default")._data)
    a = lhs.tostype("default") if not isinstance(lhs, NDArray) else lhs
    b = rhs.tostype("default") if not isinstance(rhs, NDArray) else rhs
    return NDArray(a._data + b._data)


def sparse_retain(arr, indices):
    """Public wrapper over RowSparseNDArray.retain
    (ref: src/operator/tensor/sparse_retain.cc)."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("sparse_retain expects a RowSparseNDArray")
    return arr.retain(indices)
