"""ndarray.contrib — control flow over NDArrays.

Mirrors python/mxnet/ndarray/contrib.py (foreach :135, while_loop :231,
cond :399). The loop bodies run on jax tracers inside XLA structured
control flow (lax.scan — see ops/control_flow.py), so a Gluon
HybridBlock using these compiles into one fused program; the whole loop
is recorded on the autograd tape as a single differentiable closure.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import autograd
from ..base import MXNetError
from ..ops import registry as _reg
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond", "isfinite", "isnan", "isinf"]


def _flatten_nd(args):
    """Flatten nested lists of NDArrays -> (flat list, spec)."""
    if isinstance(args, NDArray):
        return [args], None
    if not isinstance(args, (list, tuple)):
        raise MXNetError(f"expected NDArray or nested list, got {type(args)}")
    flat, spec = [], []
    for a in args:
        f, s = _flatten_nd(a)
        flat.extend(f)
        spec.append((len(f), s))
    return flat, spec


def _take(flat, spec):
    n, s = spec
    if s is None:
        return flat[0], flat[1:]
    out = []
    for sub in s:
        item, flat = _take(flat, sub)
        out.append(item)
    return out, flat


def _unflatten(flat, spec):
    """Inverse of _flatten_nd given the same spec."""
    if spec is None:
        return flat[0], flat[1:]
    return _take(flat, (len(spec), spec))


def _captured_nd(*fns):
    """NDArrays captured in the closures of the loop-body callables that
    participate in autograd (grad-attached leaves or tape outputs).

    The whole loop is recorded as ONE tape closure; anything the body
    closes over must become an explicit input of that closure or the
    backward pass cannot reach it (e.g. a weight used inside a foreach
    body — the reference's imperative loop records each op so captures
    are implicit; here the scan is opaque to the tape)."""
    seen, out, out_ids = set(), [], set()
    budget = [20000]  # hard cap on visited objects, not a silent slice

    def visit(v, depth):
        if budget[0] <= 0:
            return
        budget[0] -= 1
        if isinstance(v, NDArray):
            if id(v) not in out_ids and (
                    v.grad is not None or v._entry is not None):
                out_ids.add(id(v))
                out.append(v)
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x, depth)
        elif isinstance(v, dict):
            for x in v.values():
                visit(x, depth)
        elif callable(v) and depth < 4:
            walk(v, depth + 1)
        elif depth < 4 and hasattr(v, "__dict__") \
                and not isinstance(v, type) \
                and id(v) not in seen:
            # closed-over objects (Parameter, Block, ...) — one hop
            # through their attributes finds held arrays
            seen.add(id(v))
            visit(vars(v), depth + 1)

    def walk(f, depth=0):
        if id(f) in seen:
            return
        seen.add(id(f))
        for cell in getattr(f, "__closure__", None) or ():
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            visit(v, depth)
        # module-level arrays referenced by name (no closure cell)
        code = getattr(f, "__code__", None)
        if code is not None:
            g = getattr(f, "__globals__", {})
            for name in code.co_names:
                if name in g:
                    visit(g[name], depth)
        d = getattr(f, "__self__", None)
        if d is not None:
            visit(getattr(d, "__dict__", {}), depth)

    for f in fns:
        walk(f)
    return out


def _run_with_captured(op_call, n_explicit, captured):
    """Wrap an op call so replay-substituted values for captured arrays
    are installed into the live NDArray objects for the duration of the
    call (the body reads ``obj._data`` at trace time)."""

    def run(*datas):
        saved = [(a, a._data) for a in captured]
        try:
            for a, d in zip(captured, datas[n_explicit:]):
                a._data = d
            return op_call(*datas[:n_explicit])
        finally:
            for a, d_old in saved:
                a._data = d_old

    return run


def foreach(body, data, init_states):
    """Scan ``body`` over dim 0 of ``data``
    (ref: ndarray/contrib.py:135).

    body(data_slice, states) -> (outputs, new_states). Returns
    (outputs stacked over steps, final states).
    """
    flat_data, data_spec = _flatten_nd(data)
    flat_states, state_spec = _flatten_nd(
        init_states if isinstance(init_states, (list, tuple))
        else [init_states])
    out_spec_box = [None]

    def raw_body(xs, carry):
        xs_nd = [NDArray(x) for x in xs]
        st_nd = [NDArray(c) for c in carry]
        d, _ = _unflatten(xs_nd, data_spec)
        s, _ = _unflatten(st_nd, state_spec)
        prev = autograd.set_recording(False)
        try:
            outs, new_states = body(d, s)
        finally:
            autograd.set_recording(prev)
        flat_out, ospec = _flatten_nd(
            outs if isinstance(outs, (list, tuple)) else [outs])
        out_spec_box[0] = (ospec,
                          isinstance(outs, (list, tuple)))
        flat_new, _ = _flatten_nd(
            new_states if isinstance(new_states, (list, tuple))
            else [new_states])
        return [o._data for o in flat_out], [n._data for n in flat_new]

    op = _reg.get("_foreach")
    captured = _captured_nd(body)
    explicit = flat_data + flat_states
    inputs = explicit + captured
    run = _run_with_captured(
        lambda *d: op.fn(*d, body=raw_body, num_data=len(flat_data)),
        len(explicit), captured)

    raws = run(*[a._data for a in inputs])
    outs = [NDArray(r) for r in raws]
    if autograd.is_recording():
        autograd._record_closure("_foreach", run, inputs, outs)

    ospec, was_list = out_spec_box[0]
    n_out = len(raws) - len(flat_states)
    stacked, _ = _unflatten(outs[:n_out], ospec)
    states, _ = _unflatten(outs[n_out:], state_spec)
    if not was_list:
        stacked = stacked[0]
    if not isinstance(init_states, (list, tuple)):
        states = states[0]
    return stacked, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Bounded while loop (ref: ndarray/contrib.py:231).

    Returns (stacked step outputs padded to ``max_iterations`` with
    zeros, final loop_vars). Static trip count keeps shapes static for
    XLA; steps after the predicate fails are masked no-ops.
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    single = isinstance(loop_vars, NDArray)
    flat_vars, var_spec = _flatten_nd(
        [loop_vars] if single else loop_vars)
    out_spec_box = [None]

    def raw_cond(sts):
        st_nd = [NDArray(s) for s in sts]
        vs, _ = _unflatten(st_nd, var_spec)
        prev = autograd.set_recording(False)
        try:
            r = cond(*vs)
        finally:
            autograd.set_recording(prev)
        return r._data if isinstance(r, NDArray) else r

    def raw_func(sts):
        st_nd = [NDArray(s) for s in sts]
        vs, _ = _unflatten(st_nd, var_spec)
        prev = autograd.set_recording(False)
        try:
            outs, new_vars = func(*vs)
        finally:
            autograd.set_recording(prev)
        flat_out, ospec = _flatten_nd(
            outs if isinstance(outs, (list, tuple)) else [outs])
        out_spec_box[0] = (ospec, isinstance(outs, (list, tuple)))
        flat_new, _ = _flatten_nd(
            new_vars if isinstance(new_vars, (list, tuple))
            else [new_vars])
        return ([o._data for o in flat_out],
                [n._data for n in flat_new])

    op = _reg.get("_while_loop")
    captured = _captured_nd(cond, func)
    inputs = flat_vars + captured
    run = _run_with_captured(
        lambda *d: op.fn(*d, cond=raw_cond, func=raw_func,
                         max_iterations=max_iterations),
        len(flat_vars), captured)

    raws = run(*[a._data for a in inputs])
    outs = [NDArray(r) for r in raws[:-1]]  # last is the step counter
    if autograd.is_recording():
        autograd._record_closure("_while_loop",
                                 lambda *d: run(*d)[:-1], inputs, outs)

    ospec, was_list = out_spec_box[0]
    n_out = len(outs) - len(flat_vars)
    stacked, _ = _unflatten(outs[:n_out], ospec)
    states, _ = _unflatten(outs[n_out:], var_spec)
    if not was_list:
        stacked = stacked[0]
    if single:
        states = states[0]
    return stacked, states


def cond(pred, then_func, else_func):
    """If-then-else (ref: ndarray/contrib.py:399).

    ``pred`` is a scalar NDArray; ``then_func()``/``else_func()`` take no
    arguments and must produce outputs of matching shape/dtype. On
    concrete values one branch runs eagerly (the reference's imperative
    behaviour); on tracers (inside hybridize/jit) it lowers to lax.cond.
    """
    import jax
    from jax import lax

    p = pred._data if isinstance(pred, NDArray) else jnp.asarray(pred)
    if not isinstance(p, jax.core.Tracer):
        return then_func() if bool(p) else else_func()

    def _branch(f):
        def wrapped(_):
            out = f()
            flat, spec = _flatten_nd(
                out if isinstance(out, (list, tuple)) else [out])
            return [o._data for o in flat], spec, \
                isinstance(out, (list, tuple))
        return wrapped

    spec_box = [None]

    def then_branch(_):
        datas, spec, was_list = _branch(then_func)(None)
        spec_box[0] = (spec, was_list)
        return tuple(datas)

    def else_branch(_):
        datas, _s, _w = _branch(else_func)(None)
        return tuple(datas)

    raws = lax.cond(p.astype(bool).reshape(()), then_branch, else_branch,
                    None)
    outs = [NDArray(r) for r in raws]
    spec, was_list = spec_box[0]
    grouped, _ = _unflatten(outs, spec)
    return grouped if was_list else grouped[0]


def __getattr__(name):
    """Resolve ``nd.contrib.<op>`` to the registered ``_contrib_<op>``
    (the reference generates these at import from the C registry,
    python/mxnet/ndarray/register.py:30-60; we resolve lazily and cache
    the wrapper so repeated lookups return the same function)."""
    from .register import make_op_func
    for cand in ("_contrib_" + name, name):
        if cand in _reg._OPS:
            fn = make_op_func(_reg._OPS[cand])
            fn.__name__ = name
            globals()[name] = fn
            return fn
    raise AttributeError(f"module 'mxnet_tpu.ndarray.contrib' has no "
                         f"attribute {name!r}")


def isfinite(data):
    return NDArray(jnp.isfinite(data._data).astype(jnp.float32))


def isnan(data):
    return NDArray(jnp.isnan(data._data).astype(jnp.float32))


def isinf(data):
    return NDArray(jnp.isinf(data._data).astype(jnp.float32))
