"""mx.nd — imperative NDArray API (ref: python/mxnet/ndarray/).

Module functions for every registered op are generated at import from the op
registry (the reference does the same from the C registry via
MXSymbolGetAtomicSymbolInfo).
"""
from __future__ import annotations

import sys

import jax.numpy as jnp
import numpy as np

from .. import engine
from ..context import Context, current_context
from . import ndarray
from .ndarray import NDArray, array, concatenate, invoke
from .register import populate
from . import random  # noqa: F401
from . import contrib  # noqa: F401
from .utils import save, load, load_frombuffer


def Custom(*inputs, op_type=None, **kwargs):
    """Run a registered custom Python operator
    (ref: the generated mx.nd.Custom, src/operator/custom/custom.cc)."""
    from ..operator import invoke_custom
    if op_type is None:
        raise ValueError("Custom requires op_type=")
    return invoke_custom(list(inputs), op_type, **kwargs)

populate(globals())


# optimizer update ops: the reference mutates state inputs (mom, mean/var,
# z/n, history...) in place and returns only the weight (ref:
# src/operator/optimizer_op.cc TMutateInputs); the registry ops are pure
# and return (out, *new_states), so these wrappers restore the reference
# call surface by writing the state outputs back into the input arrays.
_UPDATE_OP_STATE_START = {
    "sgd_mom_update": 2, "mp_sgd_update": 2, "mp_sgd_mom_update": 2,
    "signum_update": 2, "adam_update": 2, "ftml_update": 2,
    "ftrl_update": 2, "rmsprop_update": 2, "rmspropalex_update": 2,
    "_sparse_adagrad_update": 2, "_contrib_group_adagrad_update": 2,
    "group_adagrad_update": 2,
}


def _make_inplace_update(name, base, state_start):
    from ..ops import registry as _opreg
    state_names = _opreg.get(name).arg_names[state_start:]

    def wrapper(*args, out=None, **kwargs):
        # states may arrive positionally or as keywords; resolve both
        # so keyword callers don't silently lose the writeback
        states = list(args[state_start:])
        for n in state_names[len(states):]:
            states.append(kwargs.get(n))
        res = base(*args, **kwargs)
        outs = list(res) if isinstance(res, (list, tuple)) else [res]
        for s, v in zip(states, outs[1:]):
            if hasattr(s, "_data"):  # only NDArrays can reflect updates
                s._data = v._data
        w = outs[0]
        if out is not None:
            out._data = w._data
            return out
        return w
    wrapper.__name__ = base.__name__
    wrapper.__doc__ = base.__doc__
    return wrapper


for _name, _start in _UPDATE_OP_STATE_START.items():
    globals()[_name] = _make_inplace_update(_name, globals()[_name], _start)
del _name, _start


# constructors shadow same-named registry wrappers (shape is positional here)
def zeros(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    ndarray.check_large_array(shape)  # refuse before the host alloc
    return array(np.zeros(shape, dtype or "float32"), ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    ndarray.check_large_array(shape)
    return array(np.ones(shape, dtype or "float32"), ctx=ctx)


def full(shape, val, ctx=None, dtype=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    ndarray.check_large_array(shape)
    return array(np.full(shape, val, dtype or "float32"), ctx=ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    return invoke("_arange", [], {"start": float(start),
                                  "stop": None if stop is None else float(stop),
                                  "step": float(step), "repeat": int(repeat),
                                  "dtype": dtype})


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return invoke("_eye", [], {"N": int(N), "M": int(M), "k": int(k),
                               "dtype": dtype})


def zeros_like(data, **kwargs):
    return invoke("zeros_like", [data], {})


def ones_like(data, **kwargs):
    return invoke("ones_like", [data], {})


def _ufunc_helper(op, scalar_op, builtin_fn):
    """array/array, array/scalar (both orders), number/number dispatch
    (ref: ndarray.py _ufunc_helper; commutative ops only)."""
    def f(lhs, rhs):
        if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
            return invoke(op, [lhs, rhs], {})
        if isinstance(lhs, NDArray):
            return invoke(scalar_op, [lhs], {"scalar": float(rhs)})
        if isinstance(rhs, NDArray):
            return invoke(scalar_op, [rhs], {"scalar": float(lhs)})
        return builtin_fn(lhs, rhs)
    return f


import builtins as _builtins  # module-scope max/min are the reduce ops

#: Elementwise max (ref: ndarray.py maximum)
maximum = _ufunc_helper("_maximum", "_maximum_scalar", _builtins.max)
#: Elementwise min (ref: ndarray.py minimum)
minimum = _ufunc_helper("_minimum", "_minimum_scalar", _builtins.min)


def moveaxis(tensor, source, destination):
    axes = list(range(tensor.ndim))
    axes.remove(source % tensor.ndim)
    axes.insert(destination % tensor.ndim, source % tensor.ndim)
    return transpose(tensor, axes=tuple(axes))  # noqa: F821


def waitall():
    engine.waitall()


def from_numpy(a, zero_copy=False):
    return array(a)


def stack(*data, axis=0):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return invoke("stack", list(data), {"axis": axis})


def concat(*data, dim=1):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return invoke("Concat", list(data), {"dim": dim})


def add_n(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return invoke("elemwise_sum", list(args), {})


def split(data, num_outputs, axis=1, squeeze_axis=False):
    out = invoke("SliceChannel", [data],
                 {"num_outputs": num_outputs, "axis": axis,
                  "squeeze_axis": squeeze_axis})
    return out if isinstance(out, (tuple, list)) else [out]


def onehot_encode(indices, out):
    return invoke("one_hot", [indices], {"depth": out.shape[1]}, out=out)
