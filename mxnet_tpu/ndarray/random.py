"""mx.nd.random — sampling namespace (ref: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .. import random as _rng
from .ndarray import NDArray, invoke

seed = _rng.seed


def _shape(shape):
    if shape is None:
        return ()
    return tuple(shape) if isinstance(shape, (tuple, list)) else (shape,)


def _sample(op, params_are_nd, nd_args, attrs):
    return invoke(op, nd_args, attrs)


def uniform(low=0, high=1, shape=None, dtype="float32", ctx=None, out=None):
    if isinstance(low, NDArray):
        return invoke("_sample_uniform", [low, high],
                      {"shape": _shape(shape), "dtype": dtype}, out=out)
    return invoke("_random_uniform", [],
                  {"low": float(low), "high": float(high),
                   "shape": _shape(shape), "dtype": dtype}, out=out)


def normal(loc=0, scale=1, shape=None, dtype="float32", ctx=None, out=None):
    if isinstance(loc, NDArray):
        return invoke("_sample_normal", [loc, scale],
                      {"shape": _shape(shape), "dtype": dtype}, out=out)
    return invoke("_random_normal", [],
                  {"loc": float(loc), "scale": float(scale),
                   "shape": _shape(shape), "dtype": dtype}, out=out)


def randn(*shape, dtype="float32", loc=0, scale=1, ctx=None):
    return normal(loc, scale, shape, dtype=dtype)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    return invoke("_random_randint", [],
                  {"low": int(low), "high": int(high), "shape": _shape(shape),
                   "dtype": dtype}, out=out)


def gamma(alpha=1, beta=1, shape=None, dtype="float32", ctx=None, out=None):
    return invoke("_random_gamma", [],
                  {"alpha": float(alpha), "beta": float(beta),
                   "shape": _shape(shape), "dtype": dtype}, out=out)


def exponential(scale=1, shape=None, dtype="float32", ctx=None, out=None):
    return invoke("_random_exponential", [],
                  {"lam": 1.0 / float(scale), "shape": _shape(shape),
                   "dtype": dtype}, out=out)


def poisson(lam=1, shape=None, dtype="float32", ctx=None, out=None):
    return invoke("_random_poisson", [],
                  {"lam": float(lam), "shape": _shape(shape), "dtype": dtype},
                  out=out)


def negative_binomial(k=1, p=1, shape=None, dtype="float32", ctx=None, out=None):
    return invoke("_random_negative_binomial", [],
                  {"k": int(k), "p": float(p), "shape": _shape(shape),
                   "dtype": dtype}, out=out)


def generalized_negative_binomial(mu=1, alpha=1, shape=None, dtype="float32",
                                  ctx=None, out=None):
    return invoke("_random_generalized_negative_binomial", [],
                  {"mu": float(mu), "alpha": float(alpha),
                   "shape": _shape(shape), "dtype": dtype}, out=out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", out=None):
    return invoke("_sample_multinomial", [data],
                  {"shape": _shape(shape), "get_prob": get_prob,
                   "dtype": dtype}, out=out)


def shuffle(data, out=None):
    return invoke("_shuffle", [data], {}, out=out)
