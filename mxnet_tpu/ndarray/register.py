"""Generate the mx.nd.* operator API from the op registry.

The reference synthesizes Python functions at import time from
MXSymbolGetAtomicSymbolInfo metadata (ref: python/mxnet/ndarray/register.py:
30-60); here the registry is Python so codegen is direct: one wrapper per
OpDef that routes NDArray arguments to the dispatch layer.
"""
from __future__ import annotations

import numpy as np

from ..ops import registry as _reg
from .ndarray import NDArray, invoke


def _is_tensor(v):
    return isinstance(v, (NDArray, np.ndarray)) or (
        hasattr(v, "shape") and hasattr(v, "dtype") and not np.isscalar(v)
    )


def make_op_func(op):
    def op_func(*args, out=None, name=None, **kwargs):
        inputs = []
        scalars = []
        for a in args:
            if a is None:
                inputs.append(None)
            elif _is_tensor(a):
                if scalars:
                    raise TypeError(
                        f"{op.name}: array argument after scalar "
                        f"parameter {scalars[-1]!r}")
                inputs.append(a if isinstance(a, NDArray) else NDArray(a))
            else:
                scalars.append(a)
        if scalars:
            # positional operator parameters after the arrays — the
            # reference's generated API accepts e.g. one_hot(idx, depth)
            # positionally (ref: python/mxnet/ndarray/register.py codegen
            # emits real named signatures)
            _reg.bind_positional_attrs(op, scalars, kwargs)
        # keyword tensor args in signature order after positionals
        for pname in op.arg_names[len(inputs):]:
            if pname in kwargs:
                v = kwargs.pop(pname)
                inputs.append(v if (v is None or isinstance(v, NDArray)) else NDArray(v))
        # num_args filtering happens in invoke() for every call path
        return invoke(op, inputs, kwargs, out=out)

    op_func.__name__ = op.name
    op_func.__doc__ = (op.fn.__doc__ or "") + f"\n\n(op: {op.name})"
    return op_func


def populate(namespace, symbolic=False, maker=None):
    """Install one function per registered op into `namespace` (a dict)."""
    maker = maker or make_op_func
    seen = {}
    for name, op in _reg.alias_map().items():
        if id(op) not in seen:
            seen[id(op)] = maker(op)
        namespace[name] = seen[id(op)]
    return namespace
