"""Per-model precision variants: one AOT-compiled executable per
(variant, shape bucket), sharing a single weight load.

The TVM playbook (PAPERS.md, arxiv 1802.04799) applied to serving:
compile once per (model, dtype, bucket) at registration, dispatch
cheaply at request time. A :class:`VariantSet` owns one replica's
executables, all committed to one device:

- ``fp32`` — the checkpoint as loaded; the gateway's correctness
  reference (batched output is bit-compared against a direct
  ``Predictor.forward`` in tests/test_serving.py).
- ``bf16`` — float params cast to bfloat16 offline, float inputs cast
  at the graph edge, outputs cast back to fp32 (the bench's headline
  inference dtype; on TPU this is the MXU-native path).
- ``int8`` — the full ``contrib/quantization.py`` ``quantize_model``
  KL/naive-calibration flow: BN folding, QuantizeGraph pass, offline
  weight quantization — run ONCE at registration. The *execution
  lowering* is then chosen per backend (the TVM/TensorRT move: one
  quantized model, per-target realizations): ``native`` runs the
  quantized graph itself (int8 MXU compute — right on TPU, where r03
  measured int8 at 2.17x fp32), ``dequant`` serves the weight-only
  realization (the offline-quantized int8 weights folded back through
  their calibrated scales into fp32 constants, original graph
  structure) on backends whose int8 compute is emulated and slower
  than fp32 — XLA CPU prices int8 dots through the scalar emitter at
  3-8x the fp32 GEMM. ``auto`` (default) picks native on tpu/axon,
  dequant elsewhere; both carry the quantization's accuracy effect.

``jax.jit`` caches one executable per input shape, so warmup over the
bucket list is exactly the AOT step: steady-state serving never
retraces (a request batch is always padded to a warmed bucket).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError

VARIANTS = ("fp32", "bf16", "int8")


def default_buckets(max_batch):
    """Powers of two up to ``max_batch`` (which is always included):
    8 -> (1, 2, 4, 8), 12 -> (1, 2, 4, 8, 12). Padding waste is
    bounded at <2x rows while the executable count stays O(log n)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise MXNetError("serving: max_batch must be >= 1")
    out = set()
    b = 1
    while b < max_batch:
        out.add(b)
        b *= 2
    out.add(max_batch)
    return tuple(sorted(out))


def pick_bucket(buckets, rows):
    """Smallest bucket >= rows (buckets is the sorted tuple)."""
    for b in buckets:
        if b >= rows:
            return b
    raise MXNetError(
        f"serving: batch of {rows} rows exceeds the largest bucket "
        f"{buckets[-1]} (admission should have rejected it)")


class VariantSet:
    """One replica's compiled forwards: ``run(variant, batch)`` where
    ``batch`` is a numpy array padded to a warmed bucket.

    Parameters mirror :class:`~mxnet_tpu.predictor.Predictor` plus the
    variant list; ``device`` pins params (and therefore compute) to one
    chip — the gateway builds one VariantSet per replica.
    """

    def __init__(self, symbol, arg_params, aux_params, input_name,
                 feature_shape, variants=("fp32",), device=None,
                 calib_data=None, calib_mode="naive",
                 excluded_sym_names=None, input_dtype="float32",
                 int8_lowering="auto", logger=logging):
        self.input_name = input_name
        self.feature_shape = tuple(int(s) for s in feature_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.device = device
        self.variants = tuple(variants)
        self.num_outputs = len(symbol.list_outputs())
        self.int8_lowering = None
        self._fns = {}
        for v in self.variants:
            if v not in VARIANTS:
                raise MXNetError(
                    f"serving: unknown variant {v!r} (have {VARIANTS})")
        if "fp32" in self.variants:
            self._fns["fp32"] = self._compile(symbol, arg_params,
                                              aux_params, cast=None)
        if "bf16" in self.variants:
            self._fns["bf16"] = self._compile(symbol, arg_params,
                                              aux_params, cast="bfloat16")
        if "int8" in self.variants:
            qsym, qarg, qaux = self._quantize(
                symbol, arg_params, aux_params, calib_data, calib_mode,
                excluded_sym_names, logger)
            if int8_lowering == "auto":
                int8_lowering = "native" if self._chip_backend() \
                    else "dequant"
            if int8_lowering == "native":
                self._fns["int8"] = self._compile(qsym, qarg, qaux,
                                                  cast=None)
            elif int8_lowering == "dequant":
                dsym, darg, daux = self._dequant_lowered(
                    symbol, arg_params, aux_params, qarg)
                self._fns["int8"] = self._compile(dsym, darg, daux,
                                                  cast=None)
            else:
                raise MXNetError(
                    f"serving: int8_lowering {int8_lowering!r} not in "
                    "('auto', 'native', 'dequant')")
            self.int8_lowering = int8_lowering

    # -- build ---------------------------------------------------------------
    def _chip_backend(self):
        import jax
        try:
            plat = (self.device.platform if self.device is not None
                    else jax.default_backend())
        except Exception:  # noqa: BLE001 — backend probe must not
            return False   # block registration
        return plat in ("tpu", "axon", "gpu")

    def _dequant_lowered(self, symbol, arg_params, aux_params, qarg):
        """Weight-only realization of the quantized model: every param
        the QuantizeGraph pass offline-quantized (``<w>_int8`` +
        calibrated ``_min``/``_max`` scales in ``qarg``) is folded back
        to fp32 through its scale, bound to the BN-folded original
        graph. Same int8 storage/accuracy story, fp32 compute — the
        lowering for backends where emulated int8 loses to fp32."""
        from ..contrib.quantization import (dequantize_offline_params,
                                            fold_batch_norm)

        fsym, farg = fold_batch_norm(symbol, arg_params, aux_params)
        out = dict(farg)
        for base, w in dequantize_offline_params(qarg).items():
            if base in out:
                out[base] = w
        return fsym, out, aux_params

    def _quantize(self, symbol, arg_params, aux_params, calib_data,
                  calib_mode, excluded_sym_names, logger):
        from ..contrib.quantization import quantize_model
        from ..io import NDArrayIter

        it = None
        if calib_mode != "none":
            if calib_data is None:
                raise MXNetError(
                    "serving: int8 variant needs calib_data (numpy "
                    "batch of representative inputs) unless "
                    "calib_mode='none'")
            calib = np.asarray(calib_data, self.input_dtype)
            it = NDArrayIter(data={self.input_name: calib},
                             batch_size=min(len(calib), 8))
        return quantize_model(
            symbol, arg_params, aux_params, calib_mode=calib_mode,
            calib_data=it,
            num_calib_examples=None if it is None else len(calib),
            excluded_sym_names=excluded_sym_names, logger=logger)

    def _compile(self, symbol, arg_params, aux_params, cast=None):
        from ..predictor import compile_symbol_forward

        bindings = dict(arg_params)
        bindings.update(aux_params)
        return compile_symbol_forward(symbol, bindings,
                                      device=self.device, cast=cast)

    # -- dispatch ------------------------------------------------------------
    def run(self, variant, batch):
        """Execute one padded batch; numpy in, list-of-numpy out (the
        ``np.asarray`` is the reply's host transfer — serving replies
        are host-bound by definition)."""
        fn, pvals = self._fns[variant]
        outs = fn(pvals, {self.input_name: np.ascontiguousarray(batch)})
        return [np.asarray(o) for o in outs]

    def warmup(self, buckets):
        """AOT-compile every (variant, bucket) executable by running a
        zeros batch through each — after this, serving never retraces.
        Returns the number of executables warmed."""
        n = 0
        for variant in self.variants:
            for b in buckets:
                zeros = np.zeros((b,) + self.feature_shape,
                                 self.input_dtype)
                self.run(variant, zeros)
                n += 1
        return n
