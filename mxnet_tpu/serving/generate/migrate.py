"""KV-block migration: move a live generation's cache between lanes.

The decode plane's failure story (and ROADMAP item 1's transfer
substrate): when a :class:`~mxnet_tpu.serving.generate.scheduler.
GenLane` dies, drains, or loses its device to a cluster reclaim, the
requests it was decoding still hold everything needed to continue
token-identically — the prompt, the accepted tokens, and (when the
device still answers) the KV blocks themselves. :class:`KVMigrator`
handles the block half:

- **salvage** (source side, before the pool closes): gather the
  request's blocks out of the dying pool's ``(layers, max_blocks,
  bt, heads, hd)`` arrays into two compact device arrays. The gather
  runs on the source device; the result owns its bytes, so the source
  pool can close immediately — no lingering reference keeps a retired
  lane's storage alive. Salvage arrays are tagged role=``kv_cache``,
  so the census accounts the in-flight bytes the whole way across.
- **land** (destination side, on the surviving lane's scheduler
  thread): ``jax.device_put`` the salvage onto the destination pool's
  placement — THE device-to-device transfer, priced against the
  ledger's HBM peak so artifacts record the handoff tax — then alloc
  destination blocks, scatter the salvage in, and hand back a
  remapped :class:`~.kvcache.BlockTable`. Pad-sink discipline is
  preserved: block 0 is never allocated, and the new table's padding
  rows still point at it.

When salvage OR landing fails (device truly gone, pool closed, or a
``migrate_wedge``/``replay_storm`` fault plan says so), the scheduler
falls back to deterministic replay — re-prefill prompt + accepted
tokens on the surviving lane; the greedy==reference contract makes
the continuation token-for-token identical either way.

No host syncs: salvage/land stay device-side end to end (the MXL002
scope covers them); only the scheduler's sanctioned token reply
transfer reads back.
"""
from __future__ import annotations

import threading

import numpy as np

from ...base import MXNetError


class MigrationError(MXNetError):
    """A KV-block migration that could not complete (dead source
    device, closed pool, wedged copy). Recoverable: the scheduler
    falls back to deterministic replay."""


class KVMigrator:
    """Block-table-aware KV handoff between two lanes' pools.

    One per :class:`GenModel`; thread-safe. Keeps running totals
    (migrations, bytes moved, priced est_s) for stats()/chaos
    artifacts, and numbers attempts so ``migrate_wedge@round=N`` can
    wedge exactly the Nth one.
    """

    def __init__(self, model_name, fault_plan=None):
        self.model_name = model_name
        # None = read MXNET_KVSTORE_FAULT_PLAN at probe time (the
        # chaos driver flips the env between scenario phases)
        self.fault_plan = fault_plan
        self._lock = threading.Lock()
        self.attempts = 0
        self.migrations = 0
        self.wedged = 0
        self.bytes_moved = 0
        self.est_s_total = 0.0

    # -- source side ---------------------------------------------------------
    def salvage(self, src_pool, block_ids):
        """Gather ``block_ids`` out of ``src_pool`` into compact device
        arrays that own their bytes (census role ``kv_cache``).

        Runs on the SOURCE device — call it before the pool closes.
        Raises :class:`MigrationError` when the blocks are gone
        (closed pool / dead device): the caller replays instead.
        """
        from ...profiling import memory as _mem

        ids = [int(b) for b in block_ids]
        if not ids:
            raise MigrationError(
                "generate: nothing to salvage (empty block table)")
        if src_pool.closed or src_pool.k is None:
            raise MigrationError(
                "generate: source pool already closed — KV blocks "
                "unsalvageable, falling back to replay")
        try:
            rows = np.asarray(ids, np.int32)
            k = _mem.tag_role(src_pool.k[:, rows], "kv_cache")
            v = _mem.tag_role(src_pool.v[:, rows], "kv_cache")
        except Exception as e:  # noqa: BLE001 — a dead device surfaces
            # here as a backend error; that IS the unsalvageable case
            raise MigrationError(
                f"generate: KV salvage failed ({e!r}) — falling back "
                "to replay") from e
        return {"k": k, "v": v, "nblocks": len(ids),
                "bytes": len(ids) * src_pool.bytes_per_block}

    # -- destination side ----------------------------------------------------
    def land(self, salvage, dst_pool, table_width):
        """Transfer ``salvage`` onto ``dst_pool``'s device, scatter it
        into freshly-allocated blocks, and return ``(table, handoff)``
        — the remapped block table plus the priced handoff report.

        Runs on the destination lane's scheduler thread (serialized
        with its decode steps, so the pool swap cannot race). The
        caller must hold a reservation covering the blocks.
        """
        import jax

        from ...tracing import clock
        from ...profiling.ledger import _peaks

        with self._lock:
            self.attempts += 1
            attempt = self.attempts
        from ...kvstore.fault import migrate_wedge_active
        if migrate_wedge_active(attempt, plan=self.fault_plan):
            with self._lock:
                self.wedged += 1
            raise MigrationError(
                "generate: migration attempt %d wedged (fault plan "
                "migrate_wedge) — falling back to replay" % attempt)
        if dst_pool.closed or dst_pool.k is None:
            raise MigrationError(
                "generate: destination pool closed mid-recovery — "
                "falling back to replay")
        from .kvcache import BlockTable

        t0 = clock.now_ns()
        n = salvage["nblocks"]
        # the device-to-device hop: re-place the salvage on the
        # destination pool's sharding (works for plain lanes and
        # tp-sliced pools alike — the pool array IS the placement)
        k_in = jax.device_put(salvage["k"], dst_pool.k.sharding)
        v_in = jax.device_put(salvage["v"], dst_pool.v.sharding)
        dst_ids = dst_pool.alloc(n)
        try:
            rows = np.asarray(dst_ids, np.int32)
            k = dst_pool.k.at[:, rows].set(k_in)
            v = dst_pool.v.at[:, rows].set(v_in)
        except Exception:
            dst_pool.free(dst_ids)
            raise
        dst_pool.swap(k, v)
        table = BlockTable(dst_pool, table_width).adopt(dst_ids)
        # price the handoff like the PR-6 ledger prices any HBM-bound
        # op: bytes over the chip's peak HBM bandwidth — the tax the
        # artifact records for every recovery
        _, peak_gbs = _peaks()
        bytes_moved = int(salvage["bytes"])
        est_s = bytes_moved / (peak_gbs * 1e9)
        with self._lock:
            self.migrations += 1
            self.bytes_moved += bytes_moved
            self.est_s_total += est_s
        return table, {
            "attempt": attempt,
            "blocks": n,
            "bytes_moved": bytes_moved,
            "est_s": est_s,
            "priced_gbps": peak_gbs,
            "wall_ns": clock.now_ns() - t0,
        }

    def stats(self):
        with self._lock:
            return {
                "attempts": self.attempts,
                "migrations": self.migrations,
                "wedged": self.wedged,
                "bytes_moved": self.bytes_moved,
                "est_s_total": self.est_s_total,
            }
