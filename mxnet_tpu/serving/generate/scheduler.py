"""Iteration-level continuous batching for generative decode
(Orca-style: the batch is re-formed every *token*, not every request).

The PR 10 gateway batches one-shot requests: a request joins exactly
one executed batch. Generation breaks that — a 500-token request and
a 5-token request in the same fixed batch would chain the short one
to the long one's tail. Here each replica lane re-forms its in-flight
batch every decode step:

- **join**: waiting requests prefill (one padded prompt each through
  the causal stack, K/V scattered into their pool blocks) and enter
  the running set *between* steps — the very next decode step carries
  them;
- **step**: one token for every running request — tokens/positions/
  block tables stacked to the smallest warmed batch bucket, one
  compiled ``decode`` call, next greedy tokens back;
- **leave**: a request that hits EOS or its ``max_new_tokens`` budget
  retires immediately — its blocks return to the pool *that step*,
  its reply stream closes, and the batch shrinks without stalling
  anyone else.

Admission is the gateway's fast-reject doctrine extended to cache
bytes: a request reserves its worst-case block budget
(``blocks_for(prompt + max_new_tokens)``) at submit; when no lane can
cover it the request raises :class:`RejectedError` with reason
``kv_cache_full`` — in the caller's thread, in microseconds, before
anything queues.

Host syncs: the scheduler's per-step device read is
:meth:`GenLane._host_tokens` — the token *reply transfer*, which by
definition must reach the host (the streaming iterator hands tokens
to clients). Everything else on the step path is host bookkeeping —
the MXL002 lint scope covers it.

**Decode failover** (docs/robustness.md "Decode failover"): a lane
that dies (:meth:`GenLane.kill`), drains (``scale_to`` shrink), or
loses its device to a cluster reclaim evacuates its in-flight
generations instead of failing them — one code path for planned and
unplanned loss. Each evacuated request's KV blocks are salvaged
through :class:`~.migrate.KVMigrator` and landed on a surviving
lane's pool (``mode=migrate``); when the blocks are unsalvageable
the survivor deterministically replays prompt + accepted tokens
(``mode=replay``) — the greedy==reference contract makes the
continuation token-identical either way, and the replayable
``stream()`` iterator gives consumers one seamless sequence. A
per-request budget (``MXTPU_GEN_MAX_RECOVERIES``, backoff base
``MXTPU_GEN_RECOVERY_BACKOFF_MS``) degrades to a fast
``RejectedError(reason="lane_lost")`` when exhausted; re-admission
re-reserves blocks atomically on the target pool, so a full pool
queues the recovery rather than double-booking.
"""
from __future__ import annotations

import threading
from collections import deque

import numpy as np

from ... import tracing
from ...telemetry import metrics as _tm
from ...tracing import clock
from ...base import MXNetError, get_env
from ..batcher import RejectedError, ServingError
from ..variants import default_buckets, pick_bucket
from .kvcache import BlockPool, BlockTable
from .migrate import KVMigrator

_met = _tm.lazy_metrics(lambda reg: {
    "requests": reg.counter(
        "mx_serving_generate_requests_total",
        "admitted generation requests", labelnames=("model",)),
    "rejected": reg.counter(
        "mx_serving_generate_rejected_total",
        "fast-rejected generation requests",
        labelnames=("model", "reason")),
    "tokens": reg.counter(
        "mx_serving_generate_tokens_total",
        "tokens through the decode plane (prefill = prompt tokens "
        "consumed, decode = tokens generated)",
        labelnames=("model", "phase")),
    "steps": reg.counter(
        "mx_serving_generate_steps_total",
        "compiled step executions", labelnames=("model", "phase")),
    "inflight": reg.gauge(
        "mx_serving_generate_inflight",
        "requests in the running decode batch",
        labelnames=("model", "lane")),
    # SAME family the one-shot gateway writes: the elastic autoscaler
    # reads mx_serving_queue_depth{model} for its pressure signal, and
    # a generator that never wrote it would read as eternally idle —
    # the policy would drain healthy decode lanes under load
    "depth": reg.gauge(
        "mx_serving_queue_depth",
        "requests pending in the model queue", labelnames=("model",)),
    "batch_rows": reg.histogram(
        "mx_serving_generate_batch_rows",
        "running requests per decode step", labelnames=("model",),
        buckets=(1, 2, 4, 8, 16, 32, 64)),
    # phase = steady | recover: the autoscaler (and anyone reading
    # latency SLOs) can see a failover stall for what it is instead
    # of mistaking it for steady-state degradation
    "ttft": reg.histogram(
        "mx_serving_generate_ttft_seconds",
        "submit -> first token (prefill + queue)",
        labelnames=("model", "phase")),
    "inter_token": reg.histogram(
        "mx_serving_generate_inter_token_seconds",
        "gap between consecutive streamed tokens of one request",
        labelnames=("model", "phase")),
    "recoveries": reg.counter(
        "mx_serving_gen_recoveries_total",
        "in-flight generations recovered onto a surviving lane "
        "(migrate = KV blocks salvaged, replay = deterministic "
        "re-decode of prompt + accepted tokens)",
        labelnames=("model", "mode")),
    "cache_blocks": reg.gauge(
        "mx_serving_generate_cache_blocks",
        "block-pool state per lane",
        labelnames=("model", "lane", "state")),
    "occupancy": reg.histogram(
        "mx_serving_generate_cache_occupancy",
        "used fraction of the block pool, sampled at every decode "
        "step", labelnames=("model",),
        buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0)),
})


class GenRequest:
    """One generation request + its streaming reply.

    ``stream()`` yields token ids as the scheduler emits them;
    ``result(timeout)`` blocks for the full greedy completion. Either
    raises the serving-side error if the request failed."""

    __slots__ = ("model", "prompt", "max_new_tokens", "trace_ctx",
                 "submit_ns", "first_token_ns", "last_token_ns",
                 "tokens", "token_spans", "step_meta", "table",
                 "next_pos", "reserved_blocks", "finish_reason",
                 "recoveries", "recover_spans", "admit_ns",
                 "kv_wait_ns", "queue_cause", "prefill_exec_ns",
                 "prompt_pad", "_kv_wait_t0", "_recover_cause",
                 "_salvage", "_recover_t0", "_recovered", "_cv",
                 "_done", "_error")

    def __init__(self, model, prompt, max_new_tokens, trace_ctx):
        self.model = model
        self.prompt = np.asarray(prompt, np.int32).ravel()
        self.max_new_tokens = int(max_new_tokens)
        self.trace_ctx = trace_ctx
        self.submit_ns = clock.now_ns()
        self.first_token_ns = 0
        self.last_token_ns = 0
        self.tokens = []
        self.token_spans = []
        self.step_meta = []       # (interleave_ns, rows, bucket)/token
        self.table = None
        self.next_pos = 0
        self.reserved_blocks = 0
        self.finish_reason = None
        self.recoveries = 0       # times this request survived a lane
        self.recover_spans = []   # (start_ns, end_ns, attrs) per rescue
        # tail-attribution decision events (profiling/tailpath.py):
        # when the request was first admitted, how long its admission
        # sat blocked on KV budget, and the dominant queue-wait cause
        self.admit_ns = 0
        self.kv_wait_ns = 0
        self.queue_cause = None
        self.prefill_exec_ns = 0
        self.prompt_pad = 0
        self._kv_wait_t0 = 0
        self._recover_cause = None
        self._salvage = None      # KV blocks gathered off a dead lane
        self._recover_t0 = 0
        self._recovered = False   # next emit is the post-rescue token
        self._cv = threading.Condition(threading.Lock())
        self._done = threading.Event()
        self._error = None

    def done(self):
        return self._done.is_set()

    def stream(self):
        """Iterate token ids as they are generated (the streaming
        reply). Replayable: every consumer streams from the first
        token, so a late (or second) reader sees the whole completion
        instead of hanging. Raises on serving-side failure."""
        i = 0
        while True:
            with self._cv:
                while i >= len(self.tokens) and not self._done.is_set():
                    self._cv.wait()
                if i >= len(self.tokens):
                    if self._error is not None:
                        raise self._error
                    return
                tok = self.tokens[i]
            yield tok
            i += 1

    def result(self, timeout=None):
        """Block for the full completion: list of generated token ids."""
        if not self._done.wait(timeout):
            raise ServingError(
                f"generate: request on {self.model!r} timed out after "
                f"{timeout}s (still queued or decoding)")
        if self._error is not None:
            raise self._error
        return list(self.tokens)

    def _push_token(self, tok):
        with self._cv:
            self.tokens.append(tok)
            self._cv.notify_all()

    def _finish(self, error=None):
        # error and done flip under the stream lock: a consumer that
        # checked `_done` while we were between the two writes would
        # wait() forever on a request that already failed — the
        # post-death stream() must observe the terminal error promptly
        with self._cv:
            self._error = error
            self._done.set()
            self._cv.notify_all()


class GenLane:
    """One decode lane: a device-pinned compiled model + block pool +
    the scheduler thread that re-forms its batch every step."""

    def __init__(self, model, idx, device, steps, pool):
        self._model = model
        self.idx = idx
        self.device = device
        self.steps = steps
        self.pool = pool
        self.waiting = deque()
        self.running = []
        self._thread = None
        # a retiring lane takes no new admissions and EVACUATES its
        # waiting+running requests onto the surviving lanes (migrate/
        # replay), then exits so the pool can be released — planned
        # scale-in, chaos kill, and ledger reclaim are one code path
        self.retiring = False
        self.cause = None        # why the lane went away (kill/reclaim)
        self.finalized = False   # pool closed + lane removed (once)

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mxtpu-generate-{self._model.name}-l{self.idx}")
        self._thread.start()

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)

    def kill(self, cause=None):
        """SIGKILL-equivalent lane loss (the chaos seam; also where a
        cluster reclaim revoking this lane's device funnels): stop
        scheduling immediately and evacuate every in-flight
        generation onto the surviving lanes — blocks migrate while
        the pool still answers, replay covers the truly-gone case."""
        m = self._model
        with m.cond:
            if self.retiring:
                return
            self.cause = cause or f"lane {self.idx} killed"
            self.retiring = True
            m.cond.notify_all()

    # -- scheduler loop ------------------------------------------------------
    def _loop(self):
        m = self._model
        while True:
            doomed = None
            admit = []
            with m.cond:
                while True:
                    if m.closed or self.retiring:
                        break
                    admit = self._pop_admissions()
                    if admit or self.running:
                        break
                    # idle, or the queue head is a recovery whose
                    # re-reservation cannot fit yet: wait for a
                    # submit or a retire freeing budget (bounded —
                    # the freeing unreserve may race this probe)
                    m.cond.wait(0.1)
                if m.closed:
                    break
                if self.retiring:
                    doomed = list(self.running) + list(self.waiting)
                    self.running = []
                    self.waiting.clear()
            if doomed is not None:
                # evacuate-then-finalize (outside the cond lock): the
                # scale-in initiator may have given up on its join
                # timeout long ago, and a pool nobody closes is a
                # permanent HBM leak
                self._evacuate(doomed)
                return
            if admit:
                m._observe_depth()     # the waiting set just shrank
            try:
                t_adm = clock.now_ns()
                for req in admit:
                    self._start(req)
                # admission work (prefill/replay/migrate landing) runs
                # BEFORE the next decode step: every already-running
                # request's next token is held behind it — the
                # prefill-interleave stall the tail plane attributes
                # per decode step (profiling/tailpath.py)
                interleave_ns = clock.now_ns() - t_adm if admit else 0
                if self.running:
                    self._step(interleave_ns)
            except Exception as e:  # noqa: BLE001 — a failed step
                # evacuates ITS requests onto the surviving lanes
                # (possibly this one); the lane survives for new work
                self._recover_inflight(admit, e)
        # shutdown: nothing new executes — fail whatever is left
        err = ServingError(
            f"generate: model {m.name!r} shut down before the request "
            "completed")
        self._fail_inflight([], err)

    def _pop_admissions(self):
        """Pop admittable waiting requests (caller holds m.cond). A
        recovery re-queued without a reservation must re-reserve
        atomically HERE, on the pool it will actually decode on — a
        full pool leaves it queued (no double-booking), to be retried
        the moment a retire frees budget."""
        m = self._model
        admit = []
        now = clock.now_ns()
        while self.waiting and \
                len(self.running) + len(admit) < m.max_decode_batch:
            req = self.waiting[0]
            if req.reserved_blocks == 0:
                need = self.pool.blocks_for(
                    len(req.prompt) + req.max_new_tokens)
                if not self.pool.reserve(need):
                    # head blocked on cache budget: from here on its
                    # queue wait is KV pressure, not backlog — the
                    # tail plane bills it to kv_wait
                    if not req._kv_wait_t0:
                        req._kv_wait_t0 = now
                    req.queue_cause = "kv_wait"
                    break
                req.reserved_blocks = need
            if req._kv_wait_t0:
                req.kv_wait_ns += max(now - req._kv_wait_t0, 0)
                req._kv_wait_t0 = 0
            if not req.admit_ns:      # first admission wins: a
                req.admit_ns = now    # recovery re-admission is billed
                                      # to recovery, not queue wait
            if req.queue_cause is None:
                req.queue_cause = "backlog" if (self.running or admit) \
                    else "none"
            self.waiting.popleft()
            admit.append(req)
        if self.waiting and \
                len(self.running) + len(admit) >= m.max_decode_batch:
            head = self.waiting[0]
            if head.queue_cause is None:
                head.queue_cause = "batch_full"
        return admit

    def _evacuate(self, doomed):
        """Retiring/killed lane: hand every admitted request to the
        surviving lanes (migrate preferred, deterministic replay as
        the fallback), then finalize. Planned drains, chaos kills,
        and ledger reclaims all exit through here."""
        m = self._model
        _met()["inflight"].labels(model=m.name,
                                  lane=str(self.idx)).set(0)
        m._observe_depth()
        m._recover_requests(
            self, doomed, self.cause or f"lane {self.idx} retired")
        m._finalize_retired_lane(self)

    def _recover_inflight(self, extra, err):
        """A failed prefill/step: route the affected requests through
        migrate/replay instead of failing them (budget-bounded — a
        persistently failing request degrades to ``lane_lost``)."""
        m = self._model
        with m.cond:
            doomed = list(self.running)
            self.running = []
        seen = set()
        uniq = []
        for req in doomed + [r for r in extra if not r.done()]:
            if id(req) not in seen:
                seen.add(id(req))
                uniq.append(req)
        _met()["inflight"].labels(model=m.name,
                                  lane=str(self.idx)).set(0)
        m._observe_depth()
        m._recover_requests(self, uniq, repr(err))

    def _fail_inflight(self, extra, err):
        m = self._model
        with m.cond:
            doomed = list(self.running) + list(self.waiting) + \
                [r for r in extra if not r.done()]
            self.running = []
            self.waiting.clear()
        # the gauges were last set with a live batch — a failed/closed
        # lane must read 0, not its final batch size forever
        _met()["inflight"].labels(model=m.name,
                                  lane=str(self.idx)).set(0)
        m._observe_depth()
        seen = set()
        for req in doomed:
            # an admitted request can sit in both `running` and
            # `extra` — retire (and close the stream of) each once
            if id(req) in seen or req.done():
                continue
            seen.add(id(req))
            self._retire(req, error=err)

    # -- phases --------------------------------------------------------------
    def _start(self, req):
        """Dispatch one admitted request: land a KV-block migration,
        deterministically replay a recovery, or fresh-prefill."""
        if req._salvage is not None and self._land_migration(req):
            return
        if req.tokens:
            self._replay(req)
        else:
            self._prefill(req)

    def _land_migration(self, req):
        """Scatter the request's salvaged KV blocks into THIS pool and
        rejoin it to the running batch — the migrate recovery mode.
        False when the landing fails (wedged/closed): the caller falls
        back to deterministic replay, which only needs the tokens."""
        m = self._model
        met = _met()
        salvage, req._salvage = req._salvage, None
        try:
            table, handoff = m.migrator.land(salvage, self.pool,
                                             m.table_width)
        except MXNetError:
            return False
        req.table = table
        # between steps the cache holds prompt + tokens[:-1]; the very
        # next decode step feeds tokens[-1] at next_pos — the invariant
        # the migrated table preserves byte-for-byte
        req.next_pos = len(req.prompt) + len(req.tokens) - 1
        now = clock.now_ns()
        req.recover_spans.append((
            req._recover_t0 or now, now,
            {"mode": "migrate", "lane": self.idx,
             "cause": req._recover_cause,
             "blocks": handoff["blocks"],
             "bytes_moved": handoff["bytes_moved"],
             "est_s": handoff["est_s"]}))
        req._recovered = True
        met["recoveries"].labels(model=m.name, mode="migrate").inc()
        self.running.append(req)
        met["inflight"].labels(model=m.name, lane=str(self.idx)).set(
            len(self.running))
        self._observe_pool()
        return True

    def _replay(self, req):
        """Deterministic replay on THIS lane: re-prefill the prompt,
        silently re-decode the already-accepted tokens (no
        re-emission — consumers see one seamless stream), rejoin the
        running batch. The greedy==reference contract makes the
        continuation token-for-token identical to the never-killed
        run; a divergence is a determinism bug and raises."""
        m = self._model
        met = _met()
        accepted = list(req.tokens)
        plen = len(req.prompt)
        tpad = pick_bucket(m.prompt_buckets, plen)
        req.table = BlockTable(self.pool, m.table_width)
        req.table.extend(self.pool.blocks_for(plen))
        tokens = np.zeros(tpad, np.int32)
        tokens[:plen] = req.prompt
        first = int(self._host_tokens(self.steps.prefill(
            tokens, plen,
            req.table.row[:tpad // self.pool.block_tokens])))
        req.next_pos = plen
        if first != accepted[0]:
            raise MXNetError(
                "generate: replay diverged at the first token "
                f"({first} != accepted {accepted[0]}) — greedy decode "
                "must be deterministic")
        # batch-1 silent re-decode through the warmed bucket: feed
        # each accepted token at its original position, checking the
        # re-derived successor — never growing past the executables
        # the lane already compiled
        bucket = pick_bucket(m.decode_buckets, 1)
        for j in range(1, len(accepted)):
            req.table.ensure_position(req.next_pos)
            tkn = np.zeros(bucket, np.int32)
            pos = np.zeros(bucket, np.int32)
            tab = np.zeros((bucket, m.table_width), np.int32)
            tkn[0] = accepted[j - 1]
            pos[0] = req.next_pos
            tab[0] = req.table.row
            nxt = int(self._host_tokens(
                self.steps.decode(tkn, pos, tab))[0])
            req.next_pos += 1
            if nxt != accepted[j]:
                raise MXNetError(
                    f"generate: replay diverged at token {j} "
                    f"({nxt} != accepted {accepted[j]}) — greedy "
                    "decode must be deterministic")
        met["tokens"].labels(model=m.name, phase="replay").inc(
            plen + max(len(accepted) - 1, 0))
        met["steps"].labels(model=m.name, phase="replay").inc(
            len(accepted))
        now = clock.now_ns()
        req.recover_spans.append((
            req._recover_t0 or now, now,
            {"mode": "replay", "lane": self.idx,
             "cause": req._recover_cause,
             "prompt_tokens": plen,
             "replayed_tokens": len(accepted)}))
        req._recovered = True
        met["recoveries"].labels(model=m.name, mode="replay").inc()
        self.running.append(req)
        met["inflight"].labels(model=m.name, lane=str(self.idx)).set(
            len(self.running))
        self._observe_pool()

    def _prefill(self, req):
        """One request's padded prompt through the causal stack; emits
        the first greedy token and joins the running set."""
        m = self._model
        met = _met()
        plen = len(req.prompt)
        tpad = pick_bucket(m.prompt_buckets, plen)
        req.table = BlockTable(self.pool, m.table_width)
        req.table.extend(self.pool.blocks_for(plen))
        tokens = np.zeros(tpad, np.int32)
        tokens[:plen] = req.prompt
        t0 = clock.now_ns()
        tok_dev = self.steps.prefill(
            tokens, plen, req.table.row[:tpad // self.pool.block_tokens])
        tok = int(self._host_tokens(tok_dev))
        req.prefill_exec_ns = clock.now_ns() - t0
        req.prompt_pad = tpad
        req.next_pos = plen
        met["tokens"].labels(model=m.name, phase="prefill").inc(plen)
        met["steps"].labels(model=m.name, phase="prefill").inc()
        self._emit(req, tok, t0, clock.now_ns(), rows=plen, bucket=tpad)
        if req.finish_reason is None:
            self.running.append(req)
            met["inflight"].labels(model=m.name,
                                   lane=str(self.idx)).set(
                len(self.running))
        else:
            self._retire(req)

    def _step(self, interleave_ns=0):
        """One iteration-level decode step over the running batch.
        ``interleave_ns`` is the admission work (prefill/replay) that
        held this step — stamped on every emitted token so the tail
        plane can blame the stall per request."""
        m = self._model
        met = _met()
        live = self.running
        bucket = pick_bucket(m.decode_buckets, len(live))
        tokens = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        tables = np.zeros((bucket, m.table_width), np.int32)
        for i, req in enumerate(live):
            req.table.ensure_position(req.next_pos)
            tokens[i] = req.tokens[-1]
            positions[i] = req.next_pos
            tables[i] = req.table.row
        t0 = clock.now_ns()
        toks = self._host_tokens(
            self.steps.decode(tokens, positions, tables))
        t1 = clock.now_ns()
        met["steps"].labels(model=m.name, phase="decode").inc()
        met["tokens"].labels(model=m.name, phase="decode").inc(len(live))
        met["batch_rows"].labels(model=m.name).observe(len(live))
        self._observe_pool()
        finished = []
        for i, req in enumerate(live):
            req.next_pos += 1
            self._emit(req, int(toks[i]), t0, t1,
                       interleave_ns=interleave_ns, rows=len(live),
                       bucket=bucket)
            if req.finish_reason is not None:
                finished.append(req)
        for req in finished:
            live.remove(req)
            self._retire(req)
        met["inflight"].labels(model=m.name, lane=str(self.idx)).set(
            len(live))

    def _host_tokens(self, tok_dev):
        """The token reply transfer: generated ids must reach the host
        to be streamed to clients (and to drive stopping + the next
        step's feed). The ONE sanctioned device read per step —
        everything else on the step path is host bookkeeping."""
        return np.asarray(tok_dev)

    def _emit(self, req, tok, step_start_ns, now_ns, interleave_ns=0,
              rows=1, bucket=1):
        """Record + stream one generated token; marks the request
        finished when it hits EOS or its budget. The step metadata
        (interleave stall, real rows, padded bucket) rides along so
        retirement can stamp it onto the token spans — the tail
        plane's per-step blame inputs."""
        m = self._model
        met = _met()
        phase = "recover" if req._recovered else "steady"
        req._recovered = False
        if not req.tokens:
            req.first_token_ns = now_ns
            met["ttft"].labels(model=m.name, phase=phase).observe(
                (now_ns - req.submit_ns) / 1e9)
        else:
            met["inter_token"].labels(
                model=m.name, phase=phase).observe(
                (now_ns - req.last_token_ns) / 1e9)
        req.last_token_ns = now_ns
        req.token_spans.append((step_start_ns, now_ns))
        req.step_meta.append((interleave_ns, rows, bucket))
        req._push_token(tok)
        if m.eos_id is not None and tok == m.eos_id:
            req.finish_reason = "eos"
        elif len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = "length"

    def _observe_pool(self):
        m = self._model
        met = _met()
        occ = self.pool.occupancy()
        lane = str(self.idx)
        for state in ("used", "free", "reserved"):
            met["cache_blocks"].labels(
                model=m.name, lane=lane, state=state).set(
                occ["%s_blocks" % state])
        met["occupancy"].labels(model=m.name).observe(occ["used_frac"])

    # -- retirement ----------------------------------------------------------
    def _retire(self, req, error=None):
        m = self._model
        if req.table is not None:
            req.table.release()
            req.table = None
        if req.reserved_blocks:
            self.pool.unreserve(req.reserved_blocks)
            req.reserved_blocks = 0
        req._salvage = None
        # the freed reservation may be exactly what a queued recovery
        # on another lane is waiting to re-reserve
        with m.cond:
            m.cond.notify_all()
        self._observe_pool()
        self._record_spans(req, error)
        req._finish(error)

    def _record_spans(self, req, error):
        m = self._model
        trace_id, parent = req.trace_ctx
        if not trace_id:
            return
        end = req.last_token_ns or clock.now_ns()
        admit_wait = max(req.admit_ns - req.submit_ns, 0) \
            if req.admit_ns else 0
        root = tracing.record_span(
            "serving.generate", trace_id, parent, req.submit_ns, end,
            cat="serving",
            attrs={"model": m.name, "lane": self.idx,
                   "prompt_tokens": len(req.prompt),
                   "new_tokens": len(req.tokens),
                   "recoveries": req.recoveries,
                   "queue_cause": req.queue_cause or "none",
                   "finish": ("error" if error is not None
                              else req.finish_reason)})
        if req.first_token_ns:
            tracing.record_span(
                "generate.prefill", trace_id, root, req.submit_ns,
                req.first_token_ns, cat="serving",
                attrs={"prompt_tokens": len(req.prompt),
                       "pad_tokens": req.prompt_pad,
                       "queue_ns": admit_wait,
                       "kv_wait_ns": req.kv_wait_ns,
                       "exec_ns": req.prefill_exec_ns})
        for s, e, attrs in req.recover_spans:
            tracing.record_span("generate.recover", trace_id, root,
                                s, e, cat="serving", attrs=attrs)
        for j, (s, e) in enumerate(req.token_spans):
            attrs = {"index": j}
            if j < len(req.step_meta):
                inter, rows, bucket = req.step_meta[j]
                attrs.update(interleave_ns=inter, rows=rows,
                             bucket=bucket)
            tracing.record_span("generate.token", trace_id, root, s, e,
                                cat="serving", attrs=attrs)


class GenModel:
    """One registered generator: decoder + N lanes + admission state.
    Built by ``Gateway.register_generator``; requests enter through
    :meth:`submit` (usually via the gateway, which owns the reject
    metrics + error messages)."""

    def __init__(self, name, decoder, devices, block_tokens,
                 max_blocks, max_new_tokens, max_decode_batch,
                 max_queue, warmup=True, tp=None, layout=None):
        self.name = name
        self.decoder = decoder
        # tp >= 2: every lane is a mesh slice (devices = list of
        # tp-device tuples); the KV pool shards its heads axis over
        # the slice and the compiled steps run as one SPMD program,
        # parameters placed from the layout plane's role table
        self.tp = tp
        self.layout = layout
        self.eos_id = decoder.eos_id
        self.block_tokens = int(block_tokens)
        self.max_blocks = int(max_blocks)
        self.max_new_tokens = int(max_new_tokens)
        self.max_decode_batch = int(max_decode_batch)
        self.max_queue = int(max_queue)
        self.closed = False
        self.cond = threading.Condition(threading.Lock())
        # decode failover: how many lane losses one request survives
        # before degrading to a fast lane_lost reject, and the backoff
        # base between REPEAT recoveries of the same request (doubling,
        # capped at 40x base — the first rescue is always immediate)
        self.max_recoveries = max(
            int(get_env("MXTPU_GEN_MAX_RECOVERIES", 2, int)), 0)
        self.recovery_backoff_ms = max(
            float(get_env("MXTPU_GEN_RECOVERY_BACKOFF_MS", 50.0,
                          float)), 0.0)
        self.fault_plan = None   # None -> MXNET_KVSTORE_FAULT_PLAN
        self.migrator = KVMigrator(name)
        self.lane_lost_rejections = 0
        self._recovery_round = 0
        bt = self.block_tokens
        max_prompt_pad = _ceil_mul(decoder.max_prompt_tokens, bt)
        # prompt pads: the PR 10 bucket ladder in units of blocks —
        # <2x pad waste, O(log n) prefill executables
        self.prompt_buckets = tuple(
            b * bt for b in default_buckets(max_prompt_pad // bt))
        self.decode_buckets = default_buckets(self.max_decode_batch)
        self.table_width = (max_prompt_pad + _ceil_mul(
            self.max_new_tokens, bt)) // bt
        capacity = self.table_width  # blocks a maximal request needs
        if capacity > self.max_blocks - 1:
            raise ServingError(
                f"generate: model {name!r} needs up to {capacity} "
                f"blocks per request but the pool only has "
                f"{self.max_blocks - 1} usable (raise "
                "MXTPU_GEN_MAX_BLOCKS or lower max_prompt_tokens/"
                "max_new_tokens)")
        self.lanes = []
        self.warmup_seconds = 0.0
        self.executables = 0
        self.degraded = False
        self._warmup_lanes = bool(warmup)
        self._next_idx = 0
        t0 = clock.now_ns()
        for device in devices:
            self.lanes.append(self._build_lane(device))
        self.warmup_seconds = (clock.now_ns() - t0) / 1e9
        for lane in self.lanes:
            lane.start()

    def _build_lane(self, device):
        """One decode lane (pool + compiled steps + scheduler), warmed
        when the model warms — registration and elastic scale-out
        share this, so a scaled-out lane is AOT-compiled exactly like
        a registered one. The caller starts it."""
        from .model import CompiledDecodeSteps
        if isinstance(device, (list, tuple)) and len(device) == 1:
            device = device[0]       # a 1-device "slice" = plain lane
        pool = BlockPool(self.decoder.num_layers,
                         self.decoder.num_heads,
                         self.decoder.head_dim, self.block_tokens,
                         self.max_blocks, device=device,
                         dtype=self.decoder.dtype)
        steps = CompiledDecodeSteps(self.decoder, pool,
                                    self.table_width, device,
                                    layout=self.layout)
        lane = GenLane(self, self._next_idx, device, steps, pool)
        self._next_idx += 1
        if self._warmup_lanes:
            self.executables += self._warmup(lane)
        return lane

    def _warmup(self, lane):
        """AOT-compile every (prefill pad, decode bucket) executable
        with pad-sink-only writes — after this, steady-state decode
        never retraces."""
        n = 0
        bt = self.block_tokens
        for tpad in self.prompt_buckets:
            lane.steps.prefill(np.zeros(tpad, np.int32), 1,
                               np.zeros(tpad // bt, np.int32))
            n += 1
        for b in self.decode_buckets:
            lane.steps.decode(np.zeros(b, np.int32),
                              np.zeros(b, np.int32),
                              np.zeros((b, self.table_width), np.int32))
            n += 1
        return n

    # -- admission -----------------------------------------------------------
    def try_admit(self, req):
        """None and an assigned lane on success, else the rejection
        reason (pure bookkeeping — fast-reject in the caller's
        thread)."""
        if self.closed:
            return "closed"
        with self.cond:
            depth = sum(len(ln.waiting) for ln in self.lanes)
            # retiring lanes drain, they do not admit — their pools
            # are about to be released
            live = [ln for ln in self.lanes if not ln.retiring]
        if not live:
            return "closed"
        if depth >= self.max_queue:
            return "queue_full"
        need = live[0].pool.blocks_for(
            len(req.prompt) + req.max_new_tokens)
        # most-headroom lane first; reservation is atomic per pool, so
        # a racing submit simply falls through to the next lane
        order = sorted(
            live,
            key=lambda ln: ln.pool.reserved_blocks())
        for lane in order:
            if lane.pool.reserve(need):
                req.reserved_blocks = need
                with self.cond:
                    if self.closed:
                        lane.pool.unreserve(need)
                        req.reserved_blocks = 0
                        return "closed"
                    if lane.retiring:
                        # scale-in landed between the reserve and the
                        # enqueue: hand the budget back and try the
                        # next lane
                        lane.pool.unreserve(need)
                        req.reserved_blocks = 0
                        continue
                    lane.waiting.append(req)
                    self.cond.notify_all()
                self._observe_depth()
                return None
        return "kv_cache_full"

    def _observe_depth(self):
        """Publish the waiting count on the shared queue-depth gauge
        (host ints under the cond lock — MXL002-safe)."""
        with self.cond:
            depth = sum(len(ln.waiting) for ln in self.lanes)
        _met()["depth"].labels(model=self.name).set(depth)

    # -- decode failover -----------------------------------------------------
    def _recover_requests(self, src_lane, reqs, cause):
        """Evacuate ``reqs`` off ``src_lane`` onto surviving lanes.

        Per request: enforce the recovery budget (exhaustion = fast
        ``lane_lost`` reject), salvage its KV blocks while the source
        pool still answers (unless a ``replay_storm`` fault forces
        the device-truly-gone case), detach it from the source pool,
        then re-admit on the lane with the most headroom — reserving
        atomically on the target, or queueing unreserved when every
        pool is full (the target's admission loop re-reserves the
        moment a retire frees budget; nothing double-books). Requests
        that never decoded a token just requeue — they lost no state,
        so they spend no budget."""
        from ...kvstore import fault as _fault
        import time as _time
        met = _met()
        reqs = [r for r in reqs if not r.done()]
        if not reqs:
            return
        with self.cond:
            self._recovery_round += 1
            rround = self._recovery_round
        storm = _fault.replay_storm_active(rround, plan=self.fault_plan)
        for req in reqs:
            # typed cause on the eventual generate.recover span: the
            # tail plane bills reclaim/drain pauses separately from
            # unplanned-crash recovery (profiling/tailpath.py)
            req._recover_cause = cause
            if self.closed:
                src_lane._retire(req, error=ServingError(
                    f"generate: model {self.name!r} shut down before "
                    "the request completed"))
                continue
            if req.tokens:
                req.recoveries += 1
                if req.recoveries > self.max_recoveries:
                    with self.cond:
                        self.lane_lost_rejections += 1
                    met["rejected"].labels(model=self.name,
                                           reason="lane_lost").inc()
                    src_lane._retire(req, error=RejectedError(
                        "lane_lost",
                        f"generate: request on {self.name!r} lost its "
                        f"lane {req.recoveries} time(s) ({cause}) and "
                        "exhausted its recovery budget (MXTPU_GEN_MAX_"
                        f"RECOVERIES={self.max_recoveries}); resubmit "
                        "to retry"))
                    continue
                if req.recoveries > 1:
                    # bounded backoff between REPEAT rescues of one
                    # request — a request ping-ponging across dying
                    # lanes must not busy-spin the recovery path. This
                    # is pacing, not polling: nothing signals "retry
                    # now", so an Event wait would just be a sleep that
                    # wakes early (no lock is held across it)
                    # mxlint: disable=MXL009
                    _time.sleep(min(
                        self.recovery_backoff_ms
                        * 2.0 ** (req.recoveries - 2),
                        self.recovery_backoff_ms * 40.0) / 1e3)
                req._recover_t0 = clock.now_ns()
                if req._salvage is None and not storm \
                        and req.table is not None and req.table.blocks:
                    try:
                        req._salvage = self.migrator.salvage(
                            src_lane.pool, req.table.blocks)
                    except MXNetError:
                        req._salvage = None   # replay covers it
            # detach from the source pool (the salvage, when taken,
            # owns its bytes — the pool can close right after)
            if req.table is not None:
                req.table.release()
                req.table = None
            if req.reserved_blocks:
                src_lane.pool.unreserve(req.reserved_blocks)
                req.reserved_blocks = 0
            req.next_pos = 0
            need = src_lane.pool.blocks_for(
                len(req.prompt) + req.max_new_tokens)
            while True:
                with self.cond:
                    live = [ln for ln in self.lanes
                            if not ln.retiring and not ln.finalized]
                if not live:
                    with self.cond:
                        self.lane_lost_rejections += 1
                    met["rejected"].labels(model=self.name,
                                           reason="lane_lost").inc()
                    src_lane._retire(req, error=RejectedError(
                        "lane_lost",
                        f"generate: model {self.name!r} has no "
                        f"surviving decode lanes to recover onto "
                        f"({cause})"))
                    break
                order = sorted(
                    live, key=lambda ln: ln.pool.reserved_blocks())
                target = None
                for ln in order:
                    if ln.pool.reserve(need):
                        req.reserved_blocks = need
                        target = ln
                        break
                if target is None:
                    # kv_cache_full during recovery: queue on the
                    # least-booked lane with NO reservation — its
                    # admission loop re-reserves atomically once a
                    # retire frees budget
                    target = order[0]
                with self.cond:
                    if not target.retiring:
                        target.waiting.append(req)
                        self.cond.notify_all()
                        break
                # the target started retiring between selection and
                # enqueue: hand the budget back and pick again
                if req.reserved_blocks:
                    target.pool.unreserve(req.reserved_blocks)
                    req.reserved_blocks = 0
        self._observe_depth()

    # -- lifecycle -----------------------------------------------------------
    def scale_to(self, n, devices, drain_timeout=30.0):
        """Resize to ``n`` decode lanes (Gateway.scale's generator
        arm). ``devices`` is the full n-lane placement (the gateway's
        picker output). Scale-out builds + warms + starts fresh lanes;
        scale-in retires the newest lanes evacuate-first: each stops
        admitting, hands its waiting+running requests to the surviving
        lanes through the migrate/replay recovery path (planned drains
        and crashes are one code path — no request waits out a drain
        timeout), and releases its KV block pool — the census
        role=kv_cache bytes drop by exactly the retired pools'
        footprint."""
        n = int(n)
        if n < 1:
            raise ServingError(
                f"generate: cannot scale {self.name!r} below 1 lane")
        with self.cond:
            active = [ln for ln in self.lanes if not ln.retiring]
        report = {"model": self.name, "from": len(active), "to": n,
                  "added": 0, "retired": 0, "freed_bytes": 0}
        if n > len(active):
            for device in devices[len(active):n]:
                lane = self._build_lane(device)
                with self.cond:
                    self.lanes.append(lane)
                lane.start()
                report["added"] += 1
        elif n < len(active):
            for lane in active[n:]:
                report["freed_bytes"] += self._retire_lane(
                    lane, timeout=drain_timeout)
                report["retired"] += 1
        return report

    def _retire_lane(self, lane, timeout=30.0):
        """Evacuate-then-retire one lane; returns the pool bytes
        released. The lane hands its admitted requests to the
        surviving lanes (KV blocks migrated, or replayed when
        unsalvageable), then exits and finalizes — typically well
        inside ``timeout``, since nothing waits for decodes to
        finish. A lane that cannot evacuate within ``timeout`` stays
        retiring (no new work) with its pool intact — closing storage
        under an in-flight copy would corrupt live requests — and
        finalizes ITSELF the moment it empties, so a timed-out
        initiator never leaks the pool."""
        from ... import tracing
        with tracing.span("elastic.drain", cat="elastic",
                          model=self.name, lane=lane.idx):
            pending = lane.pool.bytes_total
            with self.cond:
                lane.retiring = True
                self.cond.notify_all()
            lane.join(timeout)
            if lane._thread is not None and lane._thread.is_alive():
                return 0   # still draining: the lane self-finalizes
            # the lane thread usually finalized itself on its way
            # out; this call is the idempotent backstop (and the
            # whole release for lanes retired before ever starting)
            self._finalize_retired_lane(lane)
            return pending

    def _finalize_retired_lane(self, lane):
        """Close the retired lane's pool, drop it from the lane list,
        zero its gauges — exactly once, no matter whether the
        initiator's join or the lane thread's own drained-exit gets
        here first."""
        with self.cond:
            if lane.finalized:
                return 0
            lane.finalized = True
        freed = lane.pool.bytes_total
        lane.pool.close()
        with self.cond:
            if lane in self.lanes:
                self.lanes.remove(lane)
        met = _met()
        ln = str(lane.idx)
        for state in ("used", "free", "reserved"):
            met["cache_blocks"].labels(
                model=self.name, lane=ln, state=state).set(0)
        met["inflight"].labels(model=self.name, lane=ln).set(0)
        return freed

    def close(self):
        with self.cond:
            self.closed = True
            self.cond.notify_all()
        for lane in self.lanes:
            lane.join(timeout=5.0)

    def stats(self):
        with self.cond:
            waiting = sum(len(ln.waiting) for ln in self.lanes)
            running = sum(len(ln.running) for ln in self.lanes)
        return {
            "waiting": waiting,
            "running": running,
            "max_decode_batch": self.max_decode_batch,
            "max_new_tokens": self.max_new_tokens,
            "max_queue": self.max_queue,
            "prompt_buckets": list(self.prompt_buckets),
            "decode_buckets": list(self.decode_buckets),
            "table_width": self.table_width,
            "executables": self.executables,
            "warmup_seconds": round(self.warmup_seconds, 3),
            "degraded": self.degraded,
            "tp": self.tp,
            "recovery": dict(
                self.migrator.stats(),
                max_recoveries=self.max_recoveries,
                lane_lost_rejections=self.lane_lost_rejections),
            "lanes": [
                {"idx": ln.idx, "device": str(ln.device),
                 "retiring": ln.retiring,
                 "pool": ln.pool.occupancy()} for ln in self.lanes],
        }


def _ceil_mul(n, m):
    return ((int(n) + m - 1) // m) * m
