"""The decode plane's model path: a gluon decoder LM + the compiled
prefill / decode-step executables that read and write the paged cache.

The model is a plain pre-norm transformer decoder built from
``gluon.nn`` blocks (Embedding, Dense, LayerNorm) — parameters are
gluon :class:`Parameter` objects (census-tagged ``parameter`` at init,
like every other gluon model), and the *reference* path is the block's
own ``hybrid_forward`` full causal forward through the framework's op
registry (``F.flash_attention`` et al). The *serving* path extracts
the same parameter values into a pytree and compiles two pure steps
per replica lane:

- ``prefill``: one request's (padded) prompt through the stack with
  causal :func:`~mxnet_tpu.ops.pallas_kernels.flash_attention`,
  scattering every layer's K/V into the request's pool blocks and
  returning the first greedy token;
- ``decode``: one token per in-flight request (iteration-level batch),
  K/V written at each request's position, attention over the paged
  cache via :func:`~mxnet_tpu.ops.pallas_kernels.paged_attention`,
  next greedy tokens out.

Both are ``jax.jit`` executables warmed per (prompt bucket / batch
bucket) at registration — steady-state decode never retraces — with
cache arrays donated on chip backends (an un-donated cache would
double the pool's HBM every step). The layer math itself is the
framework's registered ops (``ops.nn.fully_connected`` /
``layer_norm`` / ``activation``, ``ops.tensor.embedding``) — the same
functions eager dispatch jits — so the cost/memory ledgers attribute
decode the way they attribute everything else.

:func:`reference_generate` is the correctness oracle: an *unpaged*
single-request greedy decode that re-runs the gluon block's full
causal forward per emitted token (no cache, no paging, no batching).
The gateway's paged output must match it token-for-token — the
tier-1 bitwise-greedy contract.
"""
from __future__ import annotations

import functools

import numpy as np

from ...base import MXNetError
from ...ops.nn import activation as _act
from ...ops.nn import fully_connected as _fc
from ...ops.nn import layer_norm as _ln
from ...ops.tensor import embedding as _embed


def _build_block(vocab_size, d_model, num_layers, num_heads, ff_mult,
                 dtype):
    """The gluon block: pre-norm causal transformer LM."""
    from ... import gluon
    from ...gluon import nn

    class DecoderLayer(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.ln1 = nn.LayerNorm(in_channels=d_model)
                self.qkv = nn.Dense(3 * d_model, flatten=False,
                                    in_units=d_model, dtype=dtype)
                self.proj = nn.Dense(d_model, flatten=False,
                                     in_units=d_model, dtype=dtype)
                self.ln2 = nn.LayerNorm(in_channels=d_model)
                self.ff1 = nn.Dense(ff_mult * d_model, flatten=False,
                                    in_units=d_model, dtype=dtype)
                self.ff2 = nn.Dense(d_model, flatten=False,
                                    in_units=ff_mult * d_model,
                                    dtype=dtype)

        def hybrid_forward(self, F, x):
            b, t, _ = x.shape
            h = self.ln1(x)
            qkv = self.qkv(h).reshape(b, t, 3, num_heads,
                                      d_model // num_heads)
            q = F.transpose(F.slice_axis(qkv, axis=2, begin=0, end=1)
                            .reshape(b, t, num_heads, -1),
                            axes=(0, 2, 1, 3))
            k = F.transpose(F.slice_axis(qkv, axis=2, begin=1, end=2)
                            .reshape(b, t, num_heads, -1),
                            axes=(0, 2, 1, 3))
            v = F.transpose(F.slice_axis(qkv, axis=2, begin=2, end=3)
                            .reshape(b, t, num_heads, -1),
                            axes=(0, 2, 1, 3))
            a = F.flash_attention(q, k, v, causal=True)
            a = F.transpose(a, axes=(0, 2, 1, 3)).reshape(b, t, d_model)
            x = x + self.proj(a)
            h2 = self.ln2(x)
            return x + self.ff2(F.Activation(self.ff1(h2),
                                             act_type="relu"))

    class DecoderLM(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(vocab_size, d_model,
                                          dtype=dtype)
                self.layers = []
                for i in range(num_layers):
                    layer = DecoderLayer()
                    setattr(self, "layer%d" % i, layer)
                    self.layers.append(layer)
                self.ln_f = nn.LayerNorm(in_channels=d_model)
                self.head = nn.Dense(vocab_size, use_bias=False,
                                     flatten=False, in_units=d_model,
                                     dtype=dtype)

        def hybrid_forward(self, F, tokens):
            x = self.embed(tokens)
            for layer in self.layers:
                x = layer(x)
            return self.head(self.ln_f(x))

    return DecoderLM()


class GenerativeDecoder:
    """Model + config bundle for ``Gateway.register_generator``.

    ``num_heads * head_dim == d_model``; ``max_prompt_tokens`` and the
    per-request ``max_new_tokens`` cap bound the block-table width
    (static shapes — the compiled steps never retrace in steady
    state). Parameters initialize through gluon (seed them with
    ``mx.random.seed`` for determinism).
    """

    def __init__(self, vocab_size, d_model=64, num_layers=2,
                 num_heads=4, ff_mult=4, max_prompt_tokens=64,
                 eos_id=None, dtype="float32"):
        if d_model % num_heads:
            raise MXNetError(
                f"generate: d_model {d_model} not divisible by "
                f"num_heads {num_heads}")
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = self.d_model // self.num_heads
        self.ff_mult = int(ff_mult)
        self.max_prompt_tokens = int(max_prompt_tokens)
        self.eos_id = eos_id
        self.dtype = dtype
        self.block = _build_block(self.vocab_size, self.d_model,
                                  self.num_layers, self.num_heads,
                                  self.ff_mult, dtype)
        self.block.initialize()

    # -- parameter extraction ------------------------------------------------
    def param_tree(self):
        """Structured pytree of the gluon parameters' device values
        (the compiled steps' first argument)."""
        def _v(p):
            return p.data()._data

        b = self.block
        layers = []
        for layer in b.layers:
            layers.append({
                "ln1_g": _v(layer.ln1.gamma), "ln1_b": _v(layer.ln1.beta),
                "qkv_w": _v(layer.qkv.weight), "qkv_b": _v(layer.qkv.bias),
                "proj_w": _v(layer.proj.weight),
                "proj_b": _v(layer.proj.bias),
                "ln2_g": _v(layer.ln2.gamma), "ln2_b": _v(layer.ln2.beta),
                "ff1_w": _v(layer.ff1.weight), "ff1_b": _v(layer.ff1.bias),
                "ff2_w": _v(layer.ff2.weight), "ff2_b": _v(layer.ff2.bias),
            })
        return {"embed_w": _v(b.embed.weight), "layers": layers,
                "lnf_g": _v(b.ln_f.gamma), "lnf_b": _v(b.ln_f.beta),
                "head_w": _v(b.head.weight)}

    def full_logits(self, tokens):
        """Reference path: the gluon block's own full causal forward.
        ``tokens``: int array (B, T) → logits NDArray (B, T, vocab)."""
        from ... import nd
        return self.block(nd.array(np.asarray(tokens, np.int32)))


# ---------------------------------------------------------------------------
# pure layer math (shared by prefill and decode; framework ops only)
# ---------------------------------------------------------------------------

def _layer_tail(lp, x, attn_flat):
    """Residual + projection + pre-norm MLP. Shapes (..., d)."""
    y = x + _fc(attn_flat, lp["proj_w"], lp["proj_b"], flatten=False)
    h = _ln(y, lp["ln2_g"], lp["ln2_b"])
    z = _act(_fc(h, lp["ff1_w"], lp["ff1_b"], flatten=False), "relu")
    return y + _fc(z, lp["ff2_w"], lp["ff2_b"], flatten=False)


def _final_logits(params, x):
    h = _ln(x, params["lnf_g"], params["lnf_b"])
    return _fc(h, params["head_w"], None, no_bias=True, flatten=False)


class CompiledDecodeSteps:
    """One lane's jitted prefill/decode executables, bound to a device
    and a :class:`~.kvcache.BlockPool` geometry."""

    def __init__(self, decoder, pool, table_width, device=None,
                 layout=None):
        import jax

        from ...profiling import memory as _mem

        self.decoder = decoder
        self.pool = pool
        self.table_width = int(table_width)
        self.device = device
        # donation is an HBM-residency optimization; the CPU backend
        # ignores it with a warning per call — skip it there (same
        # call as parallel/train_step.py)
        donate = jax.default_backend() != "cpu"
        if pool.mesh is not None:
            # mesh-sliced lane: parameters land under the layout
            # table's NamedShardings over the slice (the SAME table
            # training resolves through — qkv/mlp-in column-parallel,
            # proj/mlp-out row-parallel, embed/head vocab-sharded);
            # the jitted steps become one SPMD program per slice
            from ...parallel.layout import SpecLayout
            layout = layout if layout is not None \
                else SpecLayout.default()
            self.layout = layout
            shardings = layout.resolve(decoder.param_tree(), pool.mesh)
            self.params = jax.tree_util.tree_map(
                lambda a, sh: _mem.tag_role(jax.device_put(a, sh),
                                            "parameter"),
                decoder.param_tree(), shardings)
        else:
            self.layout = None
            self.params = jax.tree_util.tree_map(
                lambda a: _mem.tag_role(jax.device_put(a, device),
                                        "parameter"),
                decoder.param_tree())
        self._prefill = jax.jit(
            functools.partial(_prefill_impl, num_heads=decoder.num_heads,
                              block_tokens=pool.block_tokens),
            donate_argnums=(1, 2) if donate else ())
        self._decode = jax.jit(
            functools.partial(_decode_impl, num_heads=decoder.num_heads,
                              block_tokens=pool.block_tokens),
            donate_argnums=(1, 2) if donate else ())

    def prefill(self, tokens, n_valid, blocks):
        """Run one request's padded prompt; the pool adopts the
        written-through cache. Returns the first greedy token id (a
        device scalar — the caller's reply transfer reads it)."""
        tok, k, v = self._prefill(
            self.params, self.pool.k, self.pool.v,
            np.asarray(tokens, np.int32)[None, :],
            np.int32(n_valid), np.asarray(blocks, np.int32))
        self.pool.swap(k, v)
        return tok

    def decode(self, tokens, positions, tables):
        """One iteration-level decode step over the padded in-flight
        batch. Returns next-token ids (device array (B,))."""
        tok, k, v = self._decode(
            self.params, self.pool.k, self.pool.v,
            np.asarray(tokens, np.int32),
            np.asarray(positions, np.int32),
            np.asarray(tables, np.int32))
        self.pool.swap(k, v)
        return tok


def _prefill_impl(params, k_cache, v_cache, tokens, n_valid, blocks,
                  *, num_heads, block_tokens):
    """tokens (1, Tpad) int32, n_valid scalar, blocks (Tpad//BT,)
    int32 (tail entries = pad sink). Returns (first_token, k, v)."""
    import jax.numpy as jnp

    from ...ops.pallas_kernels import flash_attention

    b, t = tokens.shape
    x = _embed(tokens, params["embed_w"])              # (1, T, d)
    d = x.shape[-1]
    hd = d // num_heads
    nblk = t // block_tokens
    for li, lp in enumerate(params["layers"]):
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        qkv = _fc(h, lp["qkv_w"], lp["qkv_b"], flatten=False)
        q, k, v = jnp.split(qkv, 3, axis=-1)           # (1, T, d) each
        qh = q.reshape(b, t, num_heads, hd).transpose(0, 2, 1, 3)
        kh = k.reshape(b, t, num_heads, hd).transpose(0, 2, 1, 3)
        vh = v.reshape(b, t, num_heads, hd).transpose(0, 2, 1, 3)
        k_cache = k_cache.at[li, blocks].set(
            k.reshape(nblk, block_tokens, num_heads, hd))
        v_cache = v_cache.at[li, blocks].set(
            v.reshape(nblk, block_tokens, num_heads, hd))
        a = flash_attention(qh, kh, vh, causal=True)
        a = a.transpose(0, 2, 1, 3).reshape(b, t, d)
        x = _layer_tail(lp, x, a)
    logits = _final_logits(params, x)                  # (1, T, V)
    first = jnp.argmax(logits[0, n_valid - 1], axis=-1).astype(jnp.int32)
    return first, k_cache, v_cache


def _decode_impl(params, k_cache, v_cache, tokens, positions, tables,
                 *, num_heads, block_tokens):
    """tokens/positions (B,) int32, tables (B, NBMAX) int32. Padding
    rows carry position 0 and an all-pad-sink table; their output is
    discarded host-side. Returns (next_tokens, k, v)."""
    import jax.numpy as jnp

    from ...ops.pallas_kernels import paged_attention

    bsz = tokens.shape[0]
    x = _embed(tokens, params["embed_w"])              # (B, d)
    d = x.shape[-1]
    hd = d // num_heads
    rows = jnp.arange(bsz)
    blk = tables[rows, positions // block_tokens]      # (B,)
    slot = positions % block_tokens
    seq_lens = positions + 1
    for li, lp in enumerate(params["layers"]):
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        qkv = _fc(h, lp["qkv_w"], lp["qkv_b"], flatten=False)
        q, k, v = jnp.split(qkv, 3, axis=-1)           # (B, d) each
        qh = q.reshape(bsz, num_heads, hd)
        kh = k.reshape(bsz, num_heads, hd)
        vh = v.reshape(bsz, num_heads, hd)
        # the token's own K/V lands in the cache BEFORE attention —
        # position p attends over [0, p] including itself
        k_cache = k_cache.at[li, blk, slot].set(kh)
        v_cache = v_cache.at[li, blk, slot].set(vh)
        a = paged_attention(qh, k_cache[li], v_cache[li], tables,
                            seq_lens)                  # (B, H, Dh)
        x = _layer_tail(lp, x, a.reshape(bsz, d))
    logits = _final_logits(params, x)                  # (B, V)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
        k_cache, v_cache


def reference_generate(decoder, prompt, max_new_tokens):
    """Unpaged single-request greedy oracle: re-run the gluon block's
    full causal forward for every emitted token (quadratic and proud —
    no cache, no paging, no batching; the thing the decode plane must
    match token-for-token). Tokens are padded to one fixed length so
    the eager dispatch compiles a single shape."""
    prompt = [int(t) for t in np.asarray(prompt).ravel()]
    total = len(prompt) + int(max_new_tokens)
    out = []
    toks = list(prompt)
    for _ in range(int(max_new_tokens)):
        padded = np.zeros((1, total), np.int32)
        padded[0, :len(toks)] = toks
        logits = decoder.full_logits(padded).asnumpy()
        nxt = int(np.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
        if decoder.eos_id is not None and nxt == decoder.eos_id:
            break
    return out
