"""Paged KV-cache block pool (the vLLM move, framework-native).

Autoregressive decode is bounded by the KV cache, not the weights: a
request's cache grows one token per step and lives until the request
retires, so contiguous per-request buffers fragment HBM and force
worst-case reservations. The pool below carves each replica's cache
into fixed-size **token blocks** — one five-dim array pair per lane,
``(layers, max_blocks, block_tokens, heads, head_dim)`` — and gives
every request a *block table* of pool indices instead of contiguous
storage. The decode kernel (:func:`~mxnet_tpu.ops.pallas_kernels.
paged_attention`) gathers K/V straight through the table.

Accounting is the point: the two arrays are tagged role=``kv_cache``
through :mod:`mxnet_tpu.profiling.memory`, so the PR 7 census,
``mx_memory_live_bytes{role="kv_cache"}`` per-device gauges, and the
OOM postmortem all name the cache by its actual bytes — tokens/s and
occupancy measure the product, not a side-channel estimate.

Block 0 is the **pad sink**: batch-padding rows and unused prefill
tail blocks point at it, so their scatter writes land in storage no
live request reads. It is never allocated (``usable = max_blocks-1``).

Admission integration: :meth:`BlockPool.reserve` commits the
worst-case block budget of a request (``blocks_for(prompt +
max_new_tokens)``) at submit time; allocation itself is incremental
(prefill takes the prompt's blocks, decode takes one more each time a
position crosses a block boundary), so occupancy reflects live tokens
while admission can still fast-reject with ``kv_cache_full`` the
moment the pool cannot cover a request's budget.
"""
from __future__ import annotations

import threading

import numpy as np

from ...base import MXNetError

PAD_BLOCK = 0


class BlockPool:
    """One replica lane's paged KV storage + free-list + reservation
    ledger. Thread-safe: the lane scheduler allocates/frees, client
    threads reserve/unreserve at admission."""

    def __init__(self, num_layers, num_heads, head_dim, block_tokens,
                 max_blocks, device=None, dtype="float32",
                 tp_axis="tp"):
        import jax
        import jax.numpy as jnp

        from ...profiling import memory as _mem

        if max_blocks < 2:
            raise MXNetError(
                "generate: max_blocks must be >= 2 (block 0 is the "
                f"reserved pad sink), got {max_blocks}")
        if block_tokens < 1:
            raise MXNetError(
                f"generate: block_tokens must be >= 1, got {block_tokens}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_tokens = int(block_tokens)
        self.max_blocks = int(max_blocks)
        self.device = device
        self.dtype = np.dtype(dtype)
        shape = (self.num_layers, self.max_blocks, self.block_tokens,
                 self.num_heads, self.head_dim)
        # mesh-sliced lane (layout plane): ``device`` is a tuple of
        # tp devices — the pool shards its HEADS axis over the slice,
        # the same partitioning the layout table gives attention
        # weights, so each device holds exactly bytes_total/tp of
        # cache (census-verified per device, byte-exact)
        self.mesh = None
        self.tp = 1
        placement = device
        if isinstance(device, (list, tuple)) and len(device) > 1:
            from ...parallel.mesh import create_mesh
            from jax.sharding import NamedSharding, PartitionSpec as P
            if self.num_heads % len(device):
                raise MXNetError(
                    f"generate: {self.num_heads} heads do not shard "
                    f"over a tp={len(device)} slice (heads % tp must "
                    "be 0)")
            self.tp = len(device)
            self.mesh = create_mesh({tp_axis: self.tp},
                                    devices=list(device))
            placement = NamedSharding(
                self.mesh, P(None, None, None, tp_axis, None))
        elif isinstance(device, (list, tuple)):
            placement = device[0]
            self.device = device[0]
        # two separate allocations: device_put of one zeros array
        # twice returns the SAME buffer, which would alias K onto V
        # (and halve the real footprint vs the claimed one)
        self.k = jax.device_put(jnp.zeros(shape, self.dtype), placement)
        self.v = jax.device_put(jnp.zeros(shape, self.dtype), placement)
        _mem.tag_role(self.k, "kv_cache")
        _mem.tag_role(self.v, "kv_cache")
        self._lock = threading.Lock()
        # LIFO free list: recently-freed blocks are re-issued first
        # (their pool pages are the warmest)
        self._free = list(range(self.max_blocks - 1, 0, -1))
        self._reserved = 0
        self.closed = False

    # -- sizes ---------------------------------------------------------------
    @property
    def usable_blocks(self):
        return self.max_blocks - 1

    @property
    def bytes_total(self):
        """Actual device bytes of the pool (both arrays) — the number
        the census must agree with. 0 once the pool is closed (the
        arrays are released; the census kv_cache gauge must drop by
        exactly the pre-close footprint)."""
        if self.closed:
            return 0
        return int(self.k.nbytes) + int(self.v.nbytes)

    @property
    def bytes_per_block(self):
        return 2 * self.block_tokens * self.num_heads * self.head_dim \
            * self.num_layers * self.dtype.itemsize

    def blocks_for(self, tokens):
        """Blocks covering ``tokens`` cache slots (ceil division)."""
        t = int(tokens)
        return max((t + self.block_tokens - 1) // self.block_tokens, 0)

    # -- admission reservation ----------------------------------------------
    def reserve(self, nblocks):
        """Commit ``nblocks`` of worst-case budget; False when the pool
        cannot cover it (the caller fast-rejects ``kv_cache_full``)."""
        n = int(nblocks)
        with self._lock:
            if self.closed or self._reserved + n > self.usable_blocks:
                return False
            self._reserved += n
            return True

    def unreserve(self, nblocks):
        with self._lock:
            self._reserved = max(self._reserved - int(nblocks), 0)

    # -- allocation ----------------------------------------------------------
    def alloc(self, n=1):
        """Pop ``n`` block ids. A reservation-covered request can never
        see an empty free list; hitting one is a ledger bug, not load."""
        with self._lock:
            if self.closed:
                raise MXNetError(
                    "generate: alloc on a closed block pool — the "
                    "lane was retired with work still admitted "
                    "(accounting bug)")
            if n > len(self._free):
                raise MXNetError(
                    "generate: block pool exhausted (%d asked, %d free) "
                    "despite reservation — accounting bug" %
                    (n, len(self._free)))
            out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, block_ids):
        with self._lock:
            for b in block_ids:
                b = int(b)
                if b == PAD_BLOCK:
                    continue
                self._free.append(b)

    # -- state ---------------------------------------------------------------
    def used_blocks(self):
        with self._lock:
            return self.usable_blocks - len(self._free)

    def reserved_blocks(self):
        with self._lock:
            return self._reserved

    def occupancy(self):
        """Bounded snapshot for stats()/bench artifacts."""
        with self._lock:
            free = len(self._free)
            reserved = self._reserved
            closed = self.closed
        used = 0 if closed else self.usable_blocks - free
        return {
            "block_tokens": self.block_tokens,
            "usable_blocks": self.usable_blocks,
            "used_blocks": used,
            "free_blocks": free,
            "reserved_blocks": reserved,
            "used_frac": used / self.usable_blocks,
            "bytes_total": self.bytes_total,
            "bytes_per_block": self.bytes_per_block,
            "closed": closed,
        }

    def swap(self, k, v):
        """Adopt the cache arrays a (donating) jitted step returned,
        re-tagging them — donation hands back fresh jax.Array objects
        each step, and an untagged swap would silently reclassify the
        whole cache as 'activation' in the census."""
        from ...profiling import memory as _mem
        self.k = _mem.tag_role(k, "kv_cache")
        self.v = _mem.tag_role(v, "kv_cache")

    def close(self):
        """Release the pool's device arrays (lane retire, elastic
        scale-in). The K/V buffers drop their last in-pool reference
        here, so once the retired lane's compiled steps are gone the
        census role=kv_cache bytes fall by exactly ``bytes_total`` —
        the number elastic scale-in verifies. Idempotent; any later
        alloc/reserve is a ledger bug and raises."""
        with self._lock:
            if self.closed:
                return
            self.closed = True
            self.k = None
            self.v = None
            self._free = []
            self._reserved = 0


class BlockTable:
    """One request's view of the pool: orderd block ids + the fixed-
    width int32 row the decode step's gather reads (padded with the
    pad sink)."""

    __slots__ = ("pool", "blocks", "row")

    def __init__(self, pool, width):
        self.pool = pool
        self.blocks = []
        self.row = np.zeros(int(width), np.int32)

    def extend(self, n):
        """Append ``n`` freshly-allocated blocks. Capacity is checked
        BEFORE allocating, so an overflow leaves no partial state —
        freeing mid-append would return already-tracked blocks to the
        pool twice and hand one block to two requests later."""
        if n <= 0:
            return self
        if len(self.blocks) + n > len(self.row):
            raise MXNetError(
                "generate: block table overflow (%d blocks, width %d) "
                "— admission should have rejected this request"
                % (len(self.blocks) + n, len(self.row)))
        for b in self.pool.alloc(n):
            self.row[len(self.blocks)] = b
            self.blocks.append(b)
        return self

    def adopt(self, block_ids):
        """Append blocks the caller already allocated from this pool —
        the migration landing: :class:`~mxnet_tpu.serving.generate.
        migrate.KVMigrator` allocs the destination blocks, scatters the
        salvaged K/V into them, and the table only records ownership +
        the row mapping (pad-sink padding beyond the adopted prefix is
        untouched). Same overflow discipline as :meth:`extend`."""
        ids = [int(b) for b in block_ids]
        if any(b == PAD_BLOCK for b in ids):
            raise MXNetError(
                "generate: cannot adopt the pad sink into a block "
                "table — block 0 is storage no live request may own")
        if len(self.blocks) + len(ids) > len(self.row):
            raise MXNetError(
                "generate: block table overflow (%d blocks, width %d) "
                "— admission should have rejected this request"
                % (len(self.blocks) + len(ids), len(self.row)))
        for b in ids:
            self.row[len(self.blocks)] = b
            self.blocks.append(b)
        return self

    def ensure_position(self, pos):
        """Grow the table so cache position ``pos`` has a block."""
        need = pos // self.pool.block_tokens + 1 - len(self.blocks)
        if need > 0:
            self.extend(need)

    def release(self):
        self.pool.free(self.blocks)
        self.blocks = []
        self.row[:] = PAD_BLOCK
