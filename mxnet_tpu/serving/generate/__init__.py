"""Token-granular generative decode on the serving gateway.

The PR 10 gateway serves fixed-shape one-shot inference; this package
is the autoregressive half of the "millions of users" workload
(ROADMAP item 1): requests with wildly different lengths, where the
KV cache — not the weights — dominates HBM.

Three pieces, all routed through the framework's own stack:

- :mod:`.kvcache` — a **paged block pool** per replica lane: the
  cache is fixed-size token blocks + per-request block tables (the
  vLLM move), byte-accounted through the PR 7 memory census as role
  ``kv_cache`` so gauges and the OOM postmortem name it;
- :mod:`.model` — the **decoder model path**: a gluon transformer LM
  whose compiled prefill/decode steps run the framework's registered
  ops plus the Pallas kernels (causal ``flash_attention`` for
  prefill, the new single-query ``paged_attention`` for decode);
- :mod:`.scheduler` — **iteration-level continuous batching**
  (Orca-style): the in-flight decode batch is re-formed every token,
  requests join after prefill and leave at EOS/budget mid-batch, and
  admission fast-rejects ``kv_cache_full`` when the block pool cannot
  cover a request's ``max_new_tokens`` budget;
- :mod:`.migrate` — **decode failover** (docs/robustness.md "Decode
  failover"): a dying/draining lane's in-flight generations move to
  surviving lanes token-identically — KV blocks salvaged and landed
  by :class:`~.migrate.KVMigrator` when the device still answers,
  deterministic prompt+accepted-token replay when it doesn't.

Entry points: ``Gateway.register_generator`` / ``Gateway.generate``
(serving/gateway.py). Env knobs: ``MXTPU_GEN_BLOCK_TOKENS``,
``MXTPU_GEN_MAX_BLOCKS``, ``MXTPU_GEN_MAX_NEW_TOKENS``,
``MXTPU_GEN_MAX_RECOVERIES``, ``MXTPU_GEN_RECOVERY_BACKOFF_MS``.
Bench + gate: the ``generate`` stage of tools/serving_bench.py, gated
by ``tools/perf_gate.py --serving``; failover gated by the ``decode``
chaos family (``perf_gate --chaos``). Guide: docs/serving.md
"Generative decode".
"""
from __future__ import annotations

from .kvcache import PAD_BLOCK, BlockPool, BlockTable
from .migrate import KVMigrator, MigrationError
from .model import (CompiledDecodeSteps, GenerativeDecoder,
                    reference_generate)
from .scheduler import GenLane, GenModel, GenRequest

__all__ = ["PAD_BLOCK", "BlockPool", "BlockTable",
           "CompiledDecodeSteps", "GenerativeDecoder", "GenLane",
           "GenModel", "GenRequest", "KVMigrator", "MigrationError",
           "reference_generate"]
