"""Multi-tenant inference serving over the Predictor stack.

The deployment half the reference exposes as the MXPred C ABI
(``c_predict_api.h`` / predictor.py) under *production load*: many
models, many concurrent request streams, bounded tail latency.

Pieces (ROADMAP item 1, the "millions of users" direction):

- **continuous batcher** (:mod:`.batcher`) — per-model queues feed
  replica schedulers that coalesce in-flight requests into padded
  shape buckets, with a ``max_wait`` knob bounding bs=1 latency;
- **model registry + variants** (:mod:`.variants`) — each model loads
  fp32/bf16/INT8 executables (INT8 via the ``contrib/quantization.py``
  KL-calibration flow), AOT-compiled per bucket at registration so
  steady-state serving never retraces;
- **admission control + SLOs** (:mod:`.gateway`) — queue-depth and
  latency-budget fast-reject (429-style), ``mx_serving_*`` telemetry
  families, and a ``serving.request → queue → batch → execute →
  reply`` span chain per request through the PR 5 trace machinery;
- **N-replica scale-out** — request streams shard across per-device
  replicas, degrading gracefully to a single chip (SNIPPETS [2]'s
  mesh fallback), with health probes that drain and redistribute on
  failure;
- **mesh-sliced (model-sharded) lanes** (:mod:`.sharded`) — a replica
  can be a ``tp``-device submesh instead of one device: parameters
  place from the layout plane's role table
  (:class:`~mxnet_tpu.parallel.layout.SpecLayout` — the SAME table
  training resolves through), each batch runs as one SPMD program per
  slice, and the generate plane's paged KV pool shards its heads axis
  over the slice (``Gateway.register(..., tp=2)`` /
  ``register_generator(..., tp=2)`` / ``MXTPU_SERVING_TP``);
- **generative decode plane** (:mod:`.generate`) — paged KV-cache
  block pools (census role ``kv_cache``), iteration-level continuous
  batching (requests join/leave the in-flight decode batch every
  token), the single-query ``paged_attention`` Pallas kernel, and
  ``Gateway.generate()`` streaming replies with ``kv_cache_full``
  admission (knobs ``MXTPU_GEN_BLOCK_TOKENS`` /
  ``MXTPU_GEN_MAX_BLOCKS`` / ``MXTPU_GEN_MAX_NEW_TOKENS``).

Env knobs (libinfo._ENV_VARS / docs/env_vars.md):
``MXTPU_SERVING_MAX_WAIT_MS``, ``MXTPU_SERVING_MAX_QUEUE``,
``MXTPU_SERVING_SLO_MS``, ``MXTPU_SERVING_REPLICAS``,
``MXTPU_SERVING_HEALTH_SEC``. Bench + CI gate: tools/serving_bench.py
and ``tools/perf_gate.py --serving`` over
docs/artifacts/SERVING_LAST_GOOD.json. Guide: docs/serving.md.
"""
from __future__ import annotations

from .batcher import (ModelQueue, RejectedError, Request, ServingError,
                      pad_batch)
from .gateway import Gateway, Model, ModelRegistry, Replica
from .generate import (BlockPool, BlockTable, GenerativeDecoder,
                       GenModel, GenRequest, reference_generate)
from .sharded import (DIVERGENCE_BOUND, ShardedVariantSet,
                      compile_symbol_forward_sharded)
from .variants import VariantSet, default_buckets, pick_bucket

__all__ = ["BlockPool", "BlockTable", "DIVERGENCE_BOUND", "Gateway",
           "GenerativeDecoder", "GenModel", "GenRequest", "Model",
           "ModelQueue", "ModelRegistry", "RejectedError", "Replica",
           "Request", "ServingError", "ShardedVariantSet",
           "VariantSet", "compile_symbol_forward_sharded",
           "default_buckets", "pad_batch", "pick_bucket",
           "reference_generate"]
