"""Multi-tenant inference gateway: model registry + variants,
continuous batching over N replicas, SLO admission control.

One :class:`Gateway` serves many registered models concurrently. Each
model gets:

- a :class:`~mxnet_tpu.serving.batcher.ModelQueue` fed by client
  threads through :meth:`Gateway.submit` (admission-controlled), and
- N :class:`Replica` lanes — each a device + a per-(variant, bucket)
  compiled :class:`~mxnet_tpu.serving.variants.VariantSet` + one
  scheduler thread pulling coalesced batches from the shared queue.

Replica placement degrades gracefully from a multi-device mesh to a
single chip (SNIPPETS [2]'s mesh-shape fallback): asking for more
replicas than ``jax.local_devices()`` offers serves with what exists
(several replicas then share a device — still useful on CPU where XLA
runs them on pool threads) and logs the degradation.

Admission control is fast-reject (the 429 analogue): a request that
would blow the queue-depth limit or the model's latency budget
(``slo_ms``, estimated from EWMA service rates) raises
:class:`RejectedError` in the caller's thread without ever entering
the queue — overload sheds load in microseconds instead of timing
every client out.

Every request carries a trace context; at reply time the gateway
records the ``serving.request → queue / batch / execute / reply`` span
chain into the PR 5 ring (one tree per request, parented to the
client's enclosing span when there is one) and lands per-stage
latencies in the ``mx_serving_*`` telemetry families.
"""
from __future__ import annotations

import contextlib
import logging
import threading

import numpy as np

from .. import tracing
from ..base import get_env
from ..telemetry import metrics as _tm
from ..tracing import clock
from .batcher import (ModelQueue, RejectedError, Request, ServingError,
                      pad_batch)
from .variants import VariantSet, default_buckets, pick_bucket

logger = logging.getLogger(__name__)

# EWMA weight for service-rate estimates (recent batches dominate so
# admission reacts to the current load shape within ~10 batches)
_EWMA = 0.2

_met = _tm.lazy_metrics(lambda reg: {
    "requests": reg.counter(
        "mx_serving_requests_total",
        "admitted inference requests", labelnames=("model", "variant")),
    "rejected": reg.counter(
        "mx_serving_rejected_total",
        "fast-rejected requests at admission",
        labelnames=("model", "reason")),
    "batches": reg.counter(
        "mx_serving_batches_total",
        "executed batches", labelnames=("model", "variant")),
    "pad_rows": reg.counter(
        "mx_serving_padding_rows_total",
        "zero rows added to fill shape buckets", labelnames=("model",)),
    "depth": reg.gauge(
        "mx_serving_queue_depth",
        "requests pending in the model queue", labelnames=("model",)),
    "healthy": reg.gauge(
        "mx_serving_replica_healthy",
        "1 = replica serving, 0 = drained",
        labelnames=("model", "replica")),
    "failures": reg.counter(
        "mx_serving_replica_failures_total",
        "replica executions that failed and drained the replica",
        labelnames=("model",)),
    "batch_rows": reg.histogram(
        "mx_serving_batch_rows",
        "coalesced rows per executed batch", labelnames=("model",),
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256)),
    "latency": reg.histogram(
        "mx_serving_latency_seconds",
        "per-stage + end-to-end request latency",
        labelnames=("model", "stage")),
})


class Replica:
    """One serving lane: a device-pinned VariantSet + the scheduler
    thread that pulls coalesced batches for it."""

    def __init__(self, model, idx, device, variant_set):
        self._model = model
        self.idx = idx
        self.device = device
        self.variant_set = variant_set
        self.healthy = True
        self.last_error = None
        self._thread = None
        # lane generation: bumped by every start(); a scheduler thread
        # serves only its own generation, so a revive can always spawn
        # a fresh lane without racing a parked-but-still-alive one
        # (the stale lane hands back its next batch and exits)
        self._gen = 0

    def start(self):
        self._gen += 1
        self._thread = threading.Thread(
            target=self._loop, args=(self._gen,), daemon=True,
            name=f"mxtpu-serve-{self._model.name}-r{self.idx}")
        self._thread.start()

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)

    def probe(self):
        """Health check: a minimum-bucket zeros batch through every
        variant, off-queue. Healthy = all succeed."""
        vs = self.variant_set
        b = self._model.buckets[0]
        try:
            for variant in vs.variants:
                vs.run(variant, np.zeros((b,) + vs.feature_shape,
                                         vs.input_dtype))
        except Exception as e:  # noqa: BLE001 — any failure = unhealthy
            self.last_error = e
            return False
        return True

    # -- scheduler loop ------------------------------------------------------
    def _loop(self, gen):
        m = self._model
        while self.healthy and self._gen == gen and \
                not m.queue.closed:
            item = m.queue.take_batch()
            if item is None:        # closed empty
                break
            variant, batch = item
            if not self.healthy or self._gen != gen:
                # drained by a health probe (or superseded by a revived
                # lane) while blocked in take_batch: requeue instead of
                # serving on a stale/bad lane — any live lane takes it,
                # INCLUDING this replica's own fresh generation. Fail
                # directly only when nothing will ever serve it.
                err = self.last_error or ServingError("replica drained")
                if m.queue.closed:
                    for r in batch:
                        r._set_error(ServingError(
                            f"serving: model {m.name!r} shut down "
                            "before the request executed"))
                else:
                    m.queue.requeue(batch)
                    if not any(r.healthy for r in m.replicas):
                        for r in m.queue.drain():
                            r._set_error(ServingError(
                                f"serving: no healthy replica left "
                                f"for {m.name!r} (last error: "
                                f"{err!r})"))
                break
            try:
                self._run_batch(variant, batch)
            except Exception as e:  # noqa: BLE001 — one bad execution
                # drains THIS replica; the batch redistributes
                self._fail(batch, e)
        _met()["depth"].labels(model=m.name).set(m.queue.depth())

    def _run_batch(self, variant, batch):
        m = self._model
        met = _met()
        t_deq = clock.now_ns()
        rows = sum(r.rows for r in batch)
        bucket = pick_bucket(m.buckets, rows)
        for r in batch:
            r.dequeue_ns = t_deq
            r.attempts += 1
        padded, _ = pad_batch(batch, bucket,
                              self.variant_set.feature_shape,
                              self.variant_set.input_dtype)
        t0 = clock.now_ns()
        outs = self.variant_set.run(variant, padded)
        t1 = clock.now_ns()
        met["depth"].labels(model=m.name).set(m.queue.depth())
        met["batches"].labels(model=m.name, variant=variant).inc()
        met["batch_rows"].labels(model=m.name).observe(rows)
        met["pad_rows"].labels(model=m.name).inc(bucket - rows)
        off = 0
        for r in batch:
            r.exec_start_ns, r.exec_end_ns = t0, t1
            r._set_result([o[off:off + r.rows] for o in outs])
            off += r.rows
            m._reply(r, bucket=bucket, batch_size=len(batch),
                     replica=self.idx, rows=rows)
        m._observe_rate(rows, (t1 - t0) / 1e9)

    def _fail(self, batch, err):
        m = self._model
        self.healthy = False
        self.last_error = err
        met = _met()
        met["failures"].labels(model=m.name).inc()
        met["healthy"].labels(model=m.name, replica=str(self.idx)).set(0)
        logger.error("serving: replica %d of %r drained after: %r — "
                     "redistributing %d request(s)",
                     self.idx, m.name, err, len(batch))
        self._redistribute(batch, err)

    def _redistribute(self, batch, err):
        m = self._model
        survivors = [r for r in m.replicas
                     if r.healthy and r is not self]
        # a request that has failed on every replica is the poison
        # pill, not the victim — fail it instead of cycling forever
        retry = [r for r in batch if r.attempts <= len(m.replicas)]
        poison = [r for r in batch if r.attempts > len(m.replicas)]
        for r in poison:
            r._set_error(ServingError(
                f"serving: request failed on every replica of "
                f"{m.name!r}: {err!r}"))
        if survivors and retry:
            m.queue.requeue(retry)
        else:
            for r in retry:
                r._set_error(ServingError(
                    f"serving: no healthy replica left for {m.name!r} "
                    f"(last error: {err!r})"))
        # if this was the last live lane — or the survivor(s) died in
        # the same window (two replicas failing concurrently each see
        # the other as alive) — drain-fail everything still queued
        # rather than stranding it in a queue no scheduler serves
        if not any(r.healthy for r in m.replicas):
            for r in m.queue.drain():
                r._set_error(ServingError(
                    f"serving: no healthy replica left for "
                    f"{m.name!r} (last error: {err!r})"))


class Model:
    """One registered model: config + queue + replicas + service-rate
    estimates (the admission controller's inputs)."""

    def __init__(self, name, buckets, max_wait_s, max_queue, slo_s,
                 variants):
        self.name = name
        self.buckets = buckets
        self.max_queue = max_queue
        self.slo_s = slo_s
        self.variants = variants
        self.queue = ModelQueue(max_rows=buckets[-1],
                                max_wait_s=max_wait_s)
        self.replicas = []
        self._rate_lock = threading.Lock()
        self._exec_s = None       # EWMA seconds per executed batch
        self._rows_per_s = None   # EWMA serviced rows/s
        self.warmup_seconds = 0.0
        self.executables = 0
        self.degraded = False     # replicas wrap onto shared devices
        self.tp = None            # >= 2: every replica is a mesh slice
        # elasticity seams (Gateway.scale): a factory that builds one
        # more replica lane on a device, and a monotonic lane id so a
        # retired idx is never reissued to a different lane's gauges
        self._replica_factory = None
        self._next_idx = 0

    # -- service-rate estimation --------------------------------------------
    def _observe_rate(self, rows, exec_s):
        if exec_s <= 0:
            return
        with self._rate_lock:
            self._exec_s = exec_s if self._exec_s is None else \
                (1 - _EWMA) * self._exec_s + _EWMA * exec_s
            rate = rows / exec_s
            self._rows_per_s = rate if self._rows_per_s is None else \
                (1 - _EWMA) * self._rows_per_s + _EWMA * rate

    def estimate_latency_s(self, rows):
        """Predicted e2e latency for a new request: queued work drained
        at the observed rate (scaled by healthy replicas) + one
        execution. None until the first batch lands (no data = admit)."""
        with self._rate_lock:
            exec_s, rate = self._exec_s, self._rows_per_s
        if exec_s is None or not rate:
            return None
        healthy = sum(1 for r in self.replicas if r.healthy)
        if not healthy:
            return None
        backlog = self.queue.pending_rows() + rows
        return backlog / (rate * healthy) + exec_s

    # -- reply-side recording ------------------------------------------------
    def _reply(self, req, bucket, batch_size, replica, rows=None):
        t_reply = clock.now_ns()
        met = _met()
        lat = met["latency"]
        name = self.name
        lat.labels(model=name, stage="queue").observe(
            (req.dequeue_ns - req.submit_ns) / 1e9)
        lat.labels(model=name, stage="batch").observe(
            (req.exec_start_ns - req.dequeue_ns) / 1e9)
        lat.labels(model=name, stage="execute").observe(
            (req.exec_end_ns - req.exec_start_ns) / 1e9)
        lat.labels(model=name, stage="e2e").observe(
            (t_reply - req.submit_ns) / 1e9)
        trace_id, parent = req.trace_ctx
        if not trace_id:
            return
        root = tracing.record_span(
            "serving.request", trace_id, parent, req.submit_ns, t_reply,
            cat="serving",
            attrs={"model": name, "variant": req.variant,
                   "rows": req.rows, "attempts": req.attempts})
        # typed queue-wait decomposition for the tail plane
        # (profiling/tailpath.py): coalescing hold + requeue loss are
        # causes INSIDE the queue interval, stamped as attributes
        tracing.record_span("serving.queue", trace_id, root,
                            req.submit_ns, req.dequeue_ns,
                            cat="serving",
                            attrs={"hold_ns": req.hold_ns,
                                   "requeue_ns": req.requeue_ns})
        tracing.record_span("serving.batch", trace_id, root,
                            req.dequeue_ns, req.exec_start_ns,
                            cat="serving",
                            attrs={"bucket": bucket,
                                   "requests": batch_size})
        tracing.record_span("serving.execute", trace_id, root,
                            req.exec_start_ns, req.exec_end_ns,
                            cat="serving",
                            attrs={"bucket": bucket, "replica": replica,
                                   "variant": req.variant,
                                   "rows": (rows if rows is not None
                                            else req.rows)})
        tracing.record_span("serving.reply", trace_id, root,
                            req.exec_end_ns, t_reply, cat="serving")


class ModelRegistry:
    """Name -> :class:`Model`, with get-or-error semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models = {}

    def add(self, model):
        with self._lock:
            if model.name in self._models:
                raise ServingError(
                    f"serving: model {model.name!r} already registered")
            self._models[model.name] = model

    def get(self, name):
        with self._lock:
            m = self._models.get(name)
        if m is None:
            raise ServingError(
                f"serving: unknown model {name!r} (registered: "
                f"{self.names()})")
        return m

    def pop(self, name):
        with self._lock:
            return self._models.pop(name, None)

    def names(self):
        with self._lock:
            return sorted(self._models)

    def models(self):
        with self._lock:
            return list(self._models.values())


class Gateway:
    """The serving front. See the module docstring; quickstart::

        gw = serving.Gateway()
        gw.register("resnet", symbol, arg_params, aux_params,
                    input_shapes={"data": (3, 224, 224)},
                    variants=("fp32", "bf16", "int8"),
                    calib_data=calib_batch, max_batch=32)
        out = gw.infer("resnet", x, variant="int8")   # numpy in/out
        gw.close()
    """

    def __init__(self, devices=None, ledger=None,
                 ledger_owner="serving"):
        self.registry = ModelRegistry()
        self._generators = {}          # name -> generate.GenModel
        self._gen_lock = threading.Lock()
        self._devices = list(devices) if devices is not None else None
        # cluster plane (optional): when a DeviceLedger is attached,
        # every lane/slice placement is carved from the ledger's
        # usable pool (free + serving's own chips), pre-validated
        # against foreign holdings BEFORE compiling, and committed as
        # serving_lane / tp_slice leases after — the gateway's
        # exclude= discipline extended across workloads
        self._ledger = ledger
        self._ledger_owner = ledger_owner
        self._lease_deadline_s = None
        self._autoscalers = {}         # name -> elastic Autoscaler
        self._closed = False
        self._health_thread = None
        self._health_stop = threading.Event()
        period = get_env("MXTPU_SERVING_HEALTH_SEC", 0.0, float)
        if period > 0:
            self._health_thread = threading.Thread(
                target=self._health_loop, args=(period,), daemon=True,
                name="mxtpu-serve-health")
            self._health_thread.start()

    # -- cluster ledger ------------------------------------------------------
    def attach_ledger(self, ledger, owner="serving"):
        """Make ``ledger`` the assignment authority for this gateway's
        placement (see __init__); existing lanes are committed as
        leases immediately. Returns self."""
        self._ledger = ledger
        self._ledger_owner = owner
        self._ledger_sync()
        return self

    @contextlib.contextmanager
    def lease_deadline(self, seconds):
        """Scope under which placements are loan-bound: lane/slice
        leases committed inside carry ``seconds`` of deadline (the
        lending scheduler wraps its borrow-driven ``scale`` in this,
        so the ledger journal records when the chips are due back)."""
        prev = self._lease_deadline_s
        self._lease_deadline_s = float(seconds)
        try:
            yield
        finally:
            self._lease_deadline_s = prev

    def _base_devices(self):
        import jax
        return list(self._devices) if self._devices is not None \
            else jax.local_devices()

    def _usable_devices(self):
        """The placement pool: the constructor-pinned (or local-mesh)
        devices, minus chips the cluster ledger says another workload
        holds. Without a ledger this is just the base pool."""
        devs = self._base_devices()
        if self._ledger is None:
            return devs
        from ..parallel.mesh import free_pool
        return free_pool(devs, held=self._ledger.foreign_devices(
            self._ledger_owner))

    def _ledger_guard(self, devices):
        """Fail BEFORE compiling: refuse a placement that names a chip
        another workload leases. (The degraded-wrap escape hatch stays
        legal only WITHIN serving's own pool.)"""
        if self._ledger is None:
            return
        foreign = set(self._ledger.foreign_devices(self._ledger_owner))
        clash = sorted({str(d) for d in devices} & foreign)
        if clash:
            raise ServingError(
                f"serving: devices {clash} are leased to another "
                "workload in the cluster ledger — refusing the "
                "placement")

    def _ledger_sync(self):
        """Commit the CURRENT lane/slice placement as this gateway's
        leases (one per role, resized as lanes come and go; released
        when a role empties). Called after every placement-changing
        commit — register, scale, retire, unregister, close."""
        if self._ledger is None:
            return
        lanes, slices = [], []
        for m in self.registry.models():
            for rep in m.replicas:
                dev = rep.device
                if isinstance(dev, (list, tuple)) and len(dev) > 1:
                    slices.extend(dev)
                else:
                    lanes.append(dev[0] if isinstance(
                        dev, (list, tuple)) else dev)
        with self._gen_lock:
            gens = list(self._generators.values())
        for g in gens:
            for ln in g.lanes:
                dev = ln.device
                if isinstance(dev, (list, tuple)) and len(dev) > 1:
                    slices.extend(dev)
                else:
                    lanes.append(dev[0] if isinstance(
                        dev, (list, tuple)) else dev)
        slice_names = []
        for d in slices:
            n = str(d)
            if n not in slice_names:
                slice_names.append(n)
        lane_names = []
        for d in lanes:
            n = str(d)
            # degraded wrap can stack lanes (or a lane onto a slice)
            # within serving's own pool — one lease covers the chip
            if n not in lane_names and n not in slice_names:
                lane_names.append(n)
        for role, names in (("serving_lane", lane_names),
                            ("tp_slice", slice_names)):
            lease = self._ledger.find_lease(self._ledger_owner,
                                            role=role)
            if names:
                if lease is None or list(lease.devices) != names \
                        or self._lease_deadline_s is not None:
                    self._ledger.ensure(
                        self._ledger_owner, names, role=role,
                        deadline_s=self._lease_deadline_s)
            elif lease is not None:
                self._ledger.release(lease.lease_id)

    # -- registration --------------------------------------------------------
    def _sliced_devices(self):
        """Devices currently held by tp mesh-slice lanes (models and
        generators) — replicated-lane placement EXCLUDES these, so a
        wrapped bs=1 lane never silently lands on a device a sharded
        SPMD program owns (overlap only under the degraded flag)."""
        held = []
        for m in self.registry.models():
            for rep in m.replicas:
                dev = rep.device
                if isinstance(dev, (list, tuple)) and len(dev) > 1:
                    held.extend(dev)
        with self._gen_lock:
            gens = list(self._generators.values())
        for g in gens:
            for ln in g.lanes:
                dev = ln.device
                if isinstance(dev, (list, tuple)) and len(dev) > 1:
                    held.extend(dev)
        return held

    def _pick_slices(self, n, tp):
        """Mesh-slice placement for ``n`` tp-sharded lanes, carved
        from devices no other slice holds (same layout-plane
        doctrine: overlap is degraded, never silent). Scale-out picks
        only the ADDITIONAL slices through this, so a rescale never
        re-excludes the model's own devices (which would spuriously
        degrade an exactly-fitting host)."""
        from ..parallel.mesh import replica_slices, should_warn_degraded
        devs = self._usable_devices() if self._ledger is not None \
            else self._devices
        slices, degraded = replica_slices(
            n, tp, devices=devs, exclude=self._sliced_devices())
        flat = [d for s in slices for d in s]
        if degraded and should_warn_degraded(n * tp, flat):
            logger.warning(
                "serving: %d slice(s) x tp=%d requested but the free "
                "device pool cannot hold them disjointly; degrading "
                "(slices share devices)", n, tp)
        return slices, degraded

    def _pick_devices(self, n, busy=()):
        from ..parallel.mesh import replica_devices, should_warn_degraded
        # self._devices None = the full local mesh, re-read per
        # registration (a constructor-pinned pool stays pinned).
        # Devices held by tp mesh slices are excluded: a replicated
        # lane wraps onto them only when nothing else exists, and
        # then the degraded flag says so (never a silent overlap).
        # ``busy`` additionally de-prioritizes devices existing lanes
        # already occupy (scale-out passes them), so new lanes land on
        # untouched chips — freshly borrowed ones included — before
        # any wrap. With a cluster ledger the pool additionally drops
        # chips other workloads lease (a lane may NEVER wrap onto
        # those — _ledger_guard raises before any compile)
        devs = self._usable_devices() if self._ledger is not None \
            else self._devices
        picked, degraded = replica_devices(
            n, devices=devs,
            exclude=list(self._sliced_devices()) + list(busy))
        if degraded and should_warn_degraded(n, picked):
            # SNIPPETS [2] degrade pattern (parallel/mesh.py): serve
            # with the mesh that exists instead of refusing — replicas
            # wrap around onto shared devices. Warned ONCE per (ask,
            # devices): the autoscaler re-enters this on every scale
            # event, and stats() carries the degraded flag so it can
            # stop asking instead of re-triggering the wrap
            logger.warning(
                "serving: %d replicas requested but only %d local "
                "device(s); degrading (replicas share devices)",
                n, len(set(map(str, picked))))
        return picked, degraded

    def device_count(self):
        """Distinct devices available to replica placement — the
        autoscaler's non-degraded ceiling. With a cluster ledger this
        is the USABLE pool (free + serving's own), so a lend from
        training visibly raises the ceiling and a reclaim lowers it —
        the closed loop the lending scheduler steers by."""
        return len(self._usable_devices())

    def register(self, name, symbol, arg_params, aux_params,
                 input_shapes, variants=("fp32",), calib_data=None,
                 calib_mode="naive", excluded_sym_names=None,
                 buckets=None, max_batch=None, max_wait_ms=None,
                 max_queue=None, slo_ms=None, replicas=None,
                 input_dtype="float32", int8_lowering="auto",
                 warmup=True, tp=None, layout=None):
        """Register a model and AOT-compile its serving executables.

        ``input_shapes`` is ``{input_name: feature_shape}`` for the ONE
        data input — feature shape WITHOUT the batch dim (the batch dim
        is the gateway's: requests are coalesced along it).
        ``max_batch`` defaults to the largest of ``buckets`` (or 32).
        ``slo_ms`` of 0/None disables latency-budget rejection;
        ``max_wait_ms``/``max_queue``/``replicas`` default from the
        ``MXTPU_SERVING_*`` env knobs.

        ``tp >= 2`` makes every replica a **mesh slice**: a tp-device
        submesh serving one SPMD program per batch, parameters placed
        from the layout plane's role table (``layout`` overrides the
        process default) — how a model bigger than one chip serves.
        Defaults from ``MXTPU_SERVING_TP`` (0 = single-device lanes).
        """
        if self._closed:
            raise ServingError("serving: gateway is closed")
        if tp is None:
            tp = int(get_env("MXTPU_SERVING_TP", 0, int)) or None
        if tp is not None:
            tp = int(tp)
            if tp == 1:
                tp = None     # a 1-device "slice" is a plain lane
            elif tp < 1:
                raise ServingError(
                    f"serving: tp must be >= 1, got {tp}")
        if tp is not None:
            from .sharded import SHARDED_VARIANTS
            bad = [v for v in variants if v not in SHARDED_VARIANTS]
            if bad:
                raise ServingError(
                    f"serving: variants {bad} have no sharded "
                    f"lowering (tp slices serve {SHARDED_VARIANTS})")
        if len(input_shapes) != 1:
            raise ServingError(
                "serving: exactly one data input per model (got "
                f"{sorted(input_shapes)}); bake constants into params")
        (input_name, feature_shape), = input_shapes.items()
        if buckets is None:
            buckets = default_buckets(max_batch or 32)
        else:
            buckets = tuple(sorted(set(int(b) for b in buckets)))
            if max_batch is not None and buckets[-1] != int(max_batch):
                raise ServingError(
                    f"serving: max_batch {max_batch} != largest bucket "
                    f"{buckets[-1]}")
        if max_wait_ms is None:
            max_wait_ms = get_env("MXTPU_SERVING_MAX_WAIT_MS", 5.0,
                                  float)
        if max_queue is None:
            max_queue = int(get_env("MXTPU_SERVING_MAX_QUEUE", 256,
                                    int))
        if slo_ms is None:
            slo_ms = get_env("MXTPU_SERVING_SLO_MS", 0.0, float)
        if replicas is None:
            replicas = int(get_env("MXTPU_SERVING_REPLICAS", 1, int))
        if replicas < 1:
            raise ServingError(
                f"serving: replicas must be >= 1, got {replicas}")
        if name in self.registry.names():
            # fail BEFORE paying replicas x variants x buckets of
            # compilation (and before health gauges record phantom
            # replicas); registry.add re-checks authoritatively
            raise ServingError(
                f"serving: model {name!r} already registered")
        with self._gen_lock:
            if name in self._generators:
                raise ServingError(
                    f"serving: generator {name!r} already registered")
        model = Model(name, buckets, max_wait_s=max_wait_ms / 1e3,
                      max_queue=max_queue,
                      slo_s=(slo_ms / 1e3) if slo_ms else None,
                      variants=tuple(variants))
        model.tp = tp
        t0 = clock.now_ns()
        met = _met()

        def build_replica(m, idx, device):
            # the one place a serving lane is built — registration and
            # Gateway.scale (the elasticity plane) share it, so a
            # scaled-out replica is compiled/warmed exactly like a
            # registered one. ``device`` is a jax device for a plain
            # lane, or a tuple of tp devices for a mesh slice — the
            # Replica machinery (scheduler, probe, drain, scale) is
            # identical either way
            if isinstance(device, (list, tuple)) and len(device) > 1:
                from .sharded import ShardedVariantSet
                vs = ShardedVariantSet(
                    symbol, arg_params, aux_params, input_name,
                    feature_shape, devices=device, variants=variants,
                    layout=layout, input_dtype=input_dtype)
            else:
                if isinstance(device, (list, tuple)):
                    device = device[0]
                vs = VariantSet(symbol, arg_params, aux_params,
                                input_name,
                                feature_shape, variants=variants,
                                device=device, calib_data=calib_data,
                                calib_mode=calib_mode,
                                excluded_sym_names=excluded_sym_names,
                                input_dtype=input_dtype,
                                int8_lowering=int8_lowering)
            rep = Replica(m, idx, device, vs)
            executables = vs.warmup(buckets) if warmup else 0
            return rep, executables

        model._replica_factory = build_replica
        if tp is not None:
            picked, degraded = self._pick_slices(replicas, tp)
        else:
            picked, degraded = self._pick_devices(replicas)
        self._ledger_guard([d for s in picked for d in
                            (s if isinstance(s, (list, tuple))
                             else [s])])
        model.degraded = degraded
        for idx, device in enumerate(picked):
            rep, n_exec = build_replica(model, idx, device)
            model.executables += n_exec
            model.replicas.append(rep)
        model._next_idx = len(model.replicas)
        model.warmup_seconds = (clock.now_ns() - t0) / 1e9
        self.registry.add(model)
        # gauges + lanes only once registration is committed: a build
        # failing on replica k must not leave phantom healthy=1 series
        # for a model that never existed
        for rep in model.replicas:
            met["healthy"].labels(model=name,
                                  replica=str(rep.idx)).set(1)
            rep.start()
        self._ledger_sync()
        logger.info(
            "serving: registered %r — %d replica(s) x %d variant(s) x "
            "%d bucket(s), warmup %.1fs", name, len(model.replicas),
            len(model.variants), len(buckets), model.warmup_seconds)
        return model

    def register_checkpoint(self, name, prefix, epoch, input_shapes,
                            **kwargs):
        """Register from ``prefix-symbol.json`` + ``prefix-NNNN.params``
        (the MXPredCreate file contract predictor.py follows)."""
        from ..model import load_checkpoint
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return self.register(name, symbol, arg_params, aux_params,
                             input_shapes, **kwargs)

    def unregister(self, name):
        with self._gen_lock:
            gen = self._generators.pop(name, None)
        if gen is not None:
            gen.close()
        model = self.registry.pop(name)
        if model is not None:
            self._shutdown_model(model)
        self._ledger_sync()

    # -- generative decode ---------------------------------------------------
    def register_generator(self, name, decoder, block_tokens=None,
                           max_blocks=None, max_new_tokens=None,
                           max_decode_batch=8, max_queue=None,
                           replicas=None, warmup=True, tp=None,
                           layout=None):
        """Register a decoder LM for token-granular generation.

        ``decoder`` is a :class:`~.generate.GenerativeDecoder` (gluon
        transformer LM + config). Each replica lane gets a device (via
        the same ``parallel`` placement the one-shot path uses), a
        paged KV block pool of ``max_blocks`` x ``block_tokens``-token
        blocks (census role ``kv_cache``), and AOT-warmed prefill /
        decode executables — steady-state decode never retraces.
        ``max_new_tokens`` is the per-request generation cap (and the
        default for requests that don't pass one); the knob defaults
        come from ``MXTPU_GEN_BLOCK_TOKENS`` / ``MXTPU_GEN_MAX_BLOCKS``
        / ``MXTPU_GEN_MAX_NEW_TOKENS``.

        In-flight generations survive lane loss (docs/robustness.md
        "Decode failover"): a killed/drained/reclaimed lane's requests
        migrate their KV blocks to surviving lanes — or replay prompt
        + accepted tokens deterministically — and continue
        token-identically, budgeted by ``MXTPU_GEN_MAX_RECOVERIES``
        (exhaustion = fast ``RejectedError(reason="lane_lost")`` on
        the stream).
        """
        from .generate.scheduler import GenModel

        if self._closed:
            raise ServingError("serving: gateway is closed")
        if block_tokens is None:
            block_tokens = int(get_env("MXTPU_GEN_BLOCK_TOKENS", 16,
                                       int))
        if max_blocks is None:
            max_blocks = int(get_env("MXTPU_GEN_MAX_BLOCKS", 256, int))
        if max_new_tokens is None:
            max_new_tokens = int(get_env("MXTPU_GEN_MAX_NEW_TOKENS",
                                         64, int))
        if max_queue is None:
            max_queue = int(get_env("MXTPU_SERVING_MAX_QUEUE", 256,
                                    int))
        if replicas is None:
            replicas = int(get_env("MXTPU_SERVING_REPLICAS", 1, int))
        if replicas < 1:
            raise ServingError(
                f"serving: replicas must be >= 1, got {replicas}")
        if tp is None:
            tp = int(get_env("MXTPU_SERVING_TP", 0, int)) or None
        if tp is not None:
            tp = int(tp)
            if tp == 1:
                tp = None
            elif tp < 1:
                raise ServingError(
                    f"serving: tp must be >= 1, got {tp}")
        with self._gen_lock:
            if name in self._generators:
                raise ServingError(
                    f"serving: generator {name!r} already registered")
        if name in self.registry.names():
            raise ServingError(
                f"serving: model {name!r} already registered")
        if tp is not None:
            # mesh-sliced decode lanes: the paged KV pool shards its
            # heads axis over each slice, parameters place from the
            # layout table (serving/generate/model.py)
            gen_devices, gen_degraded = self._pick_slices(replicas, tp)
        else:
            gen_devices, gen_degraded = self._pick_devices(replicas)
        gen = GenModel(name, decoder,
                       devices=gen_devices,
                       block_tokens=block_tokens,
                       max_blocks=max_blocks,
                       max_new_tokens=max_new_tokens,
                       max_decode_batch=max_decode_batch,
                       max_queue=max_queue, warmup=warmup, tp=tp,
                       layout=layout)
        gen.degraded = gen_degraded
        # re-check BOTH namespaces at insert: a concurrent register()
        # or register_generator() of the same name can have landed
        # while this one paid warmup
        racing = name in self.registry.names()
        if not racing:
            with self._gen_lock:
                if name in self._generators:
                    racing = True
                else:
                    self._generators[name] = gen
        if racing:
            gen.close()
            raise ServingError(
                f"serving: model {name!r} already registered")
        logger.info(
            "serving: registered generator %r — %d lane(s), %d-token "
            "blocks x %d, %d executables, warmup %.1fs", name,
            len(gen.lanes), block_tokens, max_blocks, gen.executables,
            gen.warmup_seconds)
        return gen

    def _get_generator(self, name):
        with self._gen_lock:
            gen = self._generators.get(name)
        if gen is None:
            raise ServingError(
                f"serving: unknown generator {name!r} (registered: "
                f"{sorted(self._generators)})")
        return gen

    def submit_generate(self, model, prompt, max_new_tokens=None):
        """Admit one generation request; returns the streaming
        :class:`~.generate.GenRequest` future. Fast-rejects with
        :class:`RejectedError` (reason ``kv_cache_full`` when the
        block pool cannot cover the request's token budget)."""
        from .generate.scheduler import GenRequest, _met as _gen_met

        gen = self._get_generator(model)
        met = _gen_met()
        if max_new_tokens is None:
            max_new_tokens = gen.max_new_tokens
        prompt = np.asarray(prompt, np.int32).ravel()
        if len(prompt) < 1 or len(prompt) > gen.decoder.max_prompt_tokens:
            raise ServingError(
                f"serving: prompt of {len(prompt)} tokens outside "
                f"[1, {gen.decoder.max_prompt_tokens}] for {model!r}")
        if max_new_tokens < 1 or max_new_tokens > gen.max_new_tokens:
            raise ServingError(
                f"serving: max_new_tokens {max_new_tokens} outside "
                f"[1, {gen.max_new_tokens}] for {model!r}")
        ctx = tracing.context()
        if not ctx[0]:
            ctx = tracing.new_context()
        req = GenRequest(model, prompt, max_new_tokens, ctx)
        reason = "closed" if self._closed else gen.try_admit(req)
        if reason is not None:
            met["rejected"].labels(model=model, reason=reason).inc()
            raise RejectedError(reason, self._gen_reject_msg(
                gen, reason, len(prompt), max_new_tokens))
        met["requests"].labels(model=model).inc()
        return req

    def _gen_reject_msg(self, gen, reason, plen, max_new):
        if reason == "kv_cache_full":
            need = gen.lanes[0].pool.blocks_for(plen + max_new)
            return (f"serving: {gen.name!r} KV block pool cannot cover "
                    f"{plen}+{max_new} tokens ({need} blocks) — shed "
                    "(retry with backoff, or lower max_new_tokens)")
        if reason == "queue_full":
            return (f"serving: {gen.name!r} generation queue at depth "
                    f"limit {gen.max_queue} — shed")
        if reason == "lane_lost":
            # admission never produces this reason; the recovery path
            # builds its own message (scheduler._recover_requests) —
            # kept here so every RejectedError reason renders
            return (f"serving: {gen.name!r} request lost its decode "
                    "lane and exhausted its recovery budget — resubmit")
        return f"serving: {gen.name!r} is shutting down"

    def generate(self, model, prompt, max_new_tokens=None,
                 stream=False, timeout=120.0):
        """Greedy generation: token-id prompt in, generated token ids
        out. ``stream=True`` returns the request itself — iterate
        ``req.stream()`` for tokens as they decode."""
        req = self.submit_generate(model, prompt,
                                   max_new_tokens=max_new_tokens)
        if stream:
            return req
        return req.result(timeout)

    # -- request path --------------------------------------------------------
    def submit(self, model, data, variant="fp32"):
        """Admit + enqueue one request; returns the :class:`Request`
        future. Raises :class:`RejectedError` (fast, in the caller's
        thread) when admission sheds it."""
        m = self.registry.get(model)
        met = _met()
        if variant not in m.variants:
            raise ServingError(
                f"serving: model {model!r} has no {variant!r} variant "
                f"(registered: {m.variants})")
        arr = np.asarray(data)
        vs = m.replicas[0].variant_set
        if arr.ndim == len(vs.feature_shape):    # single sample
            arr = arr[None]
        if tuple(arr.shape[1:]) != vs.feature_shape:
            raise ServingError(
                f"serving: input shape {tuple(arr.shape)} does not "
                f"match (rows,) + {vs.feature_shape}")
        if arr.shape[0] < 1 or arr.shape[0] > m.buckets[-1]:
            raise ServingError(
                f"serving: request rows {arr.shape[0]} outside "
                f"[1, {m.buckets[-1]}] (split large batches client-"
                "side)")
        arr = arr.astype(vs.input_dtype, copy=False)
        reason = self._admit(m, arr.shape[0])
        if reason is not None:
            met["rejected"].labels(model=model, reason=reason).inc()
            raise RejectedError(reason, self._reject_msg(m, reason))
        ctx = tracing.context()
        if not ctx[0]:
            ctx = tracing.new_context()
        req = Request(model, variant, arr, ctx)
        try:
            m.queue.put(req)
        except RejectedError:
            met["rejected"].labels(model=model, reason="closed").inc()
            raise
        # counted only once actually enqueued: a closed-race request
        # must not show up as both admitted and rejected
        met["requests"].labels(model=model, variant=variant).inc()
        if not any(r.healthy for r in m.replicas):
            # the last lane died between admission and enqueue: its
            # _redistribute drain already ran, so nothing will ever
            # serve this queue — drain-fail (covers our request too)
            for r in m.queue.drain():
                r._set_error(ServingError(
                    f"serving: no healthy replica left for {model!r}"))
        met["depth"].labels(model=model).set(m.queue.depth())
        return req

    def _admit(self, m, rows):
        """None to admit, or the rejection reason. Pure bookkeeping —
        no locks beyond the queue's counters, no device work: overload
        is shed in microseconds."""
        if self._closed or m.queue.closed:
            return "closed"
        if not any(r.healthy for r in m.replicas):
            return "no_replica"
        if m.queue.depth() >= m.max_queue:
            return "queue_full"
        if m.slo_s:
            est = m.estimate_latency_s(rows)
            if est is not None and est > m.slo_s:
                return "slo"
        return None

    def _reject_msg(self, m, reason):
        if reason == "queue_full":
            return (f"serving: {m.name!r} queue at depth limit "
                    f"{m.max_queue} — shed (retry with backoff)")
        if reason == "slo":
            return (f"serving: {m.name!r} backlog would exceed the "
                    f"{m.slo_s * 1e3:.0f}ms latency budget — shed")
        if reason == "no_replica":
            return f"serving: {m.name!r} has no healthy replica"
        return f"serving: {m.name!r} is shutting down"

    def infer(self, model, data, variant="fp32", timeout=30.0):
        """Blocking request: numpy in, list-of-numpy out."""
        return self.submit(model, data, variant=variant).result(timeout)

    # -- health / introspection ---------------------------------------------
    def check_health(self, model=None, revive=True):
        """Probe every replica off-queue; drained replicas whose probe
        passes rejoin when ``revive``. Returns {model: [bool, ...]}."""
        models = [self.registry.get(model)] if model is not None \
            else self.registry.models()
        out = {}
        met = _met()
        for m in models:
            states = []
            for rep in m.replicas:
                ok = rep.probe()
                if ok and not rep.healthy and revive and \
                        not m.queue.closed:
                    rep.healthy = True
                    # always a FRESH lane: the generation bump retires
                    # any parked old scheduler (it hands back its next
                    # batch and exits), so revive can't race a thread
                    # that is mid-exit — nor leak one that isn't
                    rep.start()
                    logger.info("serving: replica %d of %r revived",
                                rep.idx, m.name)
                elif not ok and rep.healthy:
                    rep.healthy = False
                    logger.warning(
                        "serving: replica %d of %r failed its health "
                        "probe — drained", rep.idx, m.name)
                met["healthy"].labels(
                    model=m.name, replica=str(rep.idx)).set(
                        1 if rep.healthy else 0)
                states.append(rep.healthy)
            if not m.queue.closed and not any(states):
                # the probe drained the LAST lane — schedulers that
                # exit between batches never touch the queue, so
                # pending requests must drain-fail here (every other
                # no-replica path already does)
                for req in m.queue.drain():
                    req._set_error(ServingError(
                        f"serving: no healthy replica left for "
                        f"{m.name!r} (health probe drained the last "
                        "lane)"))
            out[m.name] = states
        return out

    def _health_loop(self, period):
        while not self._health_stop.wait(period):
            try:
                self.check_health()
            except Exception:  # noqa: BLE001 — health must not crash
                pass           # the gateway

    def health(self):
        """{model: [replica healthy flags]} without probing."""
        return {m.name: [r.healthy for r in m.replicas]
                for m in self.registry.models()}

    # -- elasticity (replica scaling) ----------------------------------------
    def replica_count(self, name):
        """Current serving lanes for a model or generator (retiring
        generator lanes excluded — they take no new work)."""
        with self._gen_lock:
            gen = self._generators.get(name)
        if gen is not None:
            with gen.cond:
                return sum(1 for ln in gen.lanes if not ln.retiring)
        return len(self.registry.get(name).replicas)

    def scale(self, name, replicas):
        """Resize a registered model (or generator) to ``replicas``
        serving lanes — the elasticity plane's mechanism seam
        (elastic/autoscale.py is the policy). Scale-out builds, warms,
        and starts fresh lanes through the same factory registration
        used; scale-in drains before retiring: a retired lane stops
        taking new batches, finishes (or hands back) its in-flight
        work, and only then leaves the lane list. A retiring generator
        lane EVACUATES its in-flight generations to the surviving
        lanes (KV-block migration, deterministic replay fallback —
        docs/robustness.md "Decode failover") and releases its paged
        KV block pool (census-verifiable: the role=kv_cache bytes
        drop by the pool's footprint). Returns a bounded report
        dict."""
        if self._closed:
            raise ServingError("serving: gateway is closed")
        n = int(replicas)
        if n < 1:
            raise ServingError(
                f"serving: cannot scale {name!r} below 1 replica "
                f"(asked {n}); use unregister() to remove the model")
        with self._gen_lock:
            gen = self._generators.get(name)
        if gen is not None:
            if gen.tp is not None:
                # pick only the NEW slices (the existing lanes keep
                # their devices); scale_to indexes devices[active:],
                # so the placement list is existing + new
                with gen.cond:
                    active = [ln.device for ln in gen.lanes
                              if not ln.retiring]
                extra = max(n - len(active), 0)
                if extra:
                    new_slices, new_deg = self._pick_slices(extra,
                                                            gen.tp)
                else:
                    new_slices, new_deg = [], False
                picked = list(active) + new_slices
                degraded = new_deg or \
                    n * gen.tp > self.device_count()
            else:
                # existing lanes keep their devices; only the NEW
                # lanes are placed, preferring chips no lane holds yet
                with gen.cond:
                    active = [ln.device for ln in gen.lanes
                              if not ln.retiring]
                extra = max(n - len(active), 0)
                if extra:
                    new_devs, new_deg = self._pick_devices(
                        extra, busy=active)
                else:
                    new_devs, new_deg = [], False
                picked = list(active) + new_devs
                degraded = new_deg or n > self.device_count()
            self._ledger_guard([d for s in picked for d in
                                (s if isinstance(s, (list, tuple))
                                 else [s])])
            report = gen.scale_to(n, picked)
            gen.degraded = degraded
            report["degraded"] = degraded
            self._ledger_sync()
            return report
        m = self.registry.get(name)
        cur = len(m.replicas)
        report = {"model": name, "from": cur, "to": n,
                  "added": 0, "retired": 0}
        if n == cur:
            return report
        with tracing.span("elastic.scale", cat="elastic", model=name,
                          direction="out" if n > cur else "in",
                          replicas_from=cur, replicas_to=n):
            if n > cur:
                if m.tp is not None:
                    # only the ADDITIONAL slices are placed — the
                    # existing lanes keep their devices, and the new
                    # carve excludes every held slice (own included)
                    new_slices, new_deg = self._pick_slices(n - cur,
                                                            m.tp)
                    picked = [r.device for r in m.replicas] + \
                        new_slices
                    degraded = new_deg or \
                        n * m.tp > self.device_count()
                else:
                    # place only the ADDITIONAL lanes, away from the
                    # devices the existing lanes occupy — a lend's
                    # freshly freed chips get used instead of lanes
                    # silently stacking on busy ones
                    existing = [r.device for r in m.replicas]
                    new_devs, new_deg = self._pick_devices(
                        n - cur, busy=existing)
                    picked = existing + new_devs
                    degraded = new_deg or n > self.device_count()
                self._ledger_guard([d for s in picked[cur:] for d in
                                    (s if isinstance(s, (list, tuple))
                                     else [s])])
                m.degraded = degraded
                report["degraded"] = degraded
                met = _met()
                for device in picked[cur:]:
                    idx = m._next_idx
                    m._next_idx += 1
                    rep, n_exec = m._replica_factory(m, idx, device)
                    m.executables += n_exec
                    m.replicas.append(rep)
                    met["healthy"].labels(model=name,
                                          replica=str(idx)).set(1)
                    rep.start()
                    report["added"] += 1
            else:
                # retire drained/unhealthy lanes FIRST (retiring the
                # only healthy lane would wedge the model behind dead
                # schedulers), then the newest healthy ones — the
                # oldest carry the longest-warmed executables and the
                # steadiest EWMAs
                doomed = sorted(
                    m.replicas,
                    key=lambda r: (r.healthy, -r.idx))[:cur - n]
                for rep in doomed:
                    self._retire_replica(m, rep)
                    report["retired"] += 1
                # shrinking can also UN-degrade: stats() must reflect
                # the new width or the autoscaler never asks again
                # (a tp model needs n slices x tp devices)
                m.degraded = n * (m.tp or 1) > self.device_count()
                report["degraded"] = m.degraded
        self._ledger_sync()
        return report

    def _retire_replica(self, m, rep):
        """Drain-before-retire (the PR-10 drain seam, minus the
        failure): the lane stops at its next take_batch wakeup — a
        batch it already pulled is requeued to survivors, a batch it
        is mid-executing completes and replies normally — and the
        lane leaves the list immediately so admission, poison
        accounting, and health all see the new width."""
        with tracing.span("elastic.drain", cat="elastic", model=m.name,
                          replica=rep.idx):
            rep.healthy = False
            rep.last_error = ServingError(
                f"serving: replica {rep.idx} of {m.name!r} retired by "
                "scale-in")
            rep._gen += 1   # a parked scheduler hands its batch back
            if rep in m.replicas:
                m.replicas.remove(rep)
            _met()["healthy"].labels(model=m.name,
                                     replica=str(rep.idx)).set(0)
            # best-effort join: a lane parked in take_batch on an idle
            # queue exits at its next wakeup (daemon thread, reaped by
            # the interpreter) — retirement must not block on traffic
            rep.join(timeout=0.5)
        self._ledger_sync()

    def attach_autoscaler(self, name, scaler):
        """Expose an autoscaler's daemon health through stats() — the
        scaler calls this at start(), so a dead policy loop is visible
        where operators already look instead of failing silently."""
        self._autoscalers[name] = scaler

    def stats(self):
        """Bounded per-model snapshot (queue depth, service-rate
        estimates, replica states, executables compiled)."""
        out = {}
        for m in self.registry.models():
            with m._rate_lock:
                exec_s, rate = m._exec_s, m._rows_per_s
            out[m.name] = {
                "queue_depth": m.queue.depth(),
                "pending_rows": m.queue.pending_rows(),
                "buckets": list(m.buckets),
                "variants": list(m.variants),
                "max_queue": m.max_queue,
                "slo_ms": m.slo_s * 1e3 if m.slo_s else None,
                "max_wait_ms": m.queue.max_wait_s * 1e3,
                "replicas": [
                    {"idx": r.idx, "device": str(r.device),
                     "healthy": r.healthy} for r in m.replicas],
                # the degraded-wrap flag (replicas sharing devices):
                # the autoscaler reads it to stop asking for lanes the
                # hardware cannot isolate (satellite of the mesh
                # warning dedupe — warn once, expose the state here)
                "degraded": m.degraded,
                # >= 2: every replica is a tp mesh slice (sharded.py)
                "tp": m.tp,
                "int8_lowering": (m.replicas[0].variant_set
                                  .int8_lowering if m.replicas
                                  else None),
                "ewma_exec_s": exec_s,
                "ewma_rows_per_s": rate,
                "executables": m.executables,
                "warmup_seconds": round(m.warmup_seconds, 3),
            }
        with self._gen_lock:
            gens = list(self._generators.values())
        for g in gens:
            out[g.name] = {"generator": True, **g.stats()}
        # daemon health of attached autoscalers: a policy loop that
        # died (or is stuck retrying) must be visible here, not only
        # in the logs it failed to write
        for name, scaler in list(self._autoscalers.items()):
            out.setdefault(name, {})["autoscaler"] = \
                scaler.daemon_stats()
        return out

    # -- shutdown ------------------------------------------------------------
    def _shutdown_model(self, model):
        model.queue.close()
        for rep in model.replicas:
            rep.join(timeout=5.0)
        for req in model.queue.drain():
            req._set_error(ServingError(
                f"serving: model {model.name!r} shut down before the "
                "request executed"))

    def close(self):
        """Drain and stop everything; pending requests fail cleanly."""
        if self._closed:
            return
        self._closed = True
        self._health_stop.set()
        with self._gen_lock:
            gen_names = sorted(self._generators)
        for name in gen_names:
            self.unregister(name)
        for name in self.registry.names():
            self.unregister(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
