"""Mesh-sliced serving: a replica lane that is a tp submesh, not a
device — the model-sharded half of the layout plane.

The PR 10 gateway's :class:`~mxnet_tpu.serving.variants.VariantSet`
commits one replica's parameters to ONE device; a model bigger than a
chip simply could not register. A :class:`ShardedVariantSet` commits
them to a **mesh slice** instead: ``tp`` devices form a one-axis
:class:`~jax.sharding.Mesh`, every parameter lands under the
:class:`~mxnet_tpu.parallel.layout.SpecLayout` table's NamedSharding
for its role — the SAME table training resolves through — and each
padded batch executes as ONE jitted SPMD program per slice (GSPMD
inserts the row-parallel all-reduces; the column-parallel chain splits
no contraction and stays mathematically exact).

Numerics contract: a tp-sharded fp32 forward may differ from the
single-device reference by reduction reassociation on the row-parallel
layers — bounded, measured, and committed (``serving_bench`` stage
``sharded`` pins the divergence against :data:`DIVERGENCE_BOUND`;
bitwise when the layout resolves column-parallel only).

Placement hygiene: slices come from
:func:`~mxnet_tpu.parallel.mesh.replica_slices`, and the gateway
excludes slice-held devices when wrapping replicated bs=1 lanes — a
sliced and a wrapped lane never share a device unless the ``degraded``
flag says so.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError, get_env
from ..parallel.layout import SpecLayout
from ..parallel.mesh import create_mesh

#: documented fp32 divergence ceiling of a tp-sharded forward vs the
#: single-device reference (reduction reassociation on row-parallel
#: layers; serving_bench commits the measured number against it)
DIVERGENCE_BOUND = 5e-5

SHARDED_VARIANTS = ("fp32", "bf16")


def compile_symbol_forward_sharded(symbol, bindings, mesh, layout,
                                   cast=None):
    """The sharded twin of :func:`~mxnet_tpu.predictor.
    compile_symbol_forward`: commit ``bindings`` under the layout
    table's NamedShardings over ``mesh`` and return ``(jitted,
    param_vals)`` where ``jitted(param_vals, inputs_dict)`` runs the
    symbol as one SPMD program with replicated (host-gatherable)
    outputs."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ndarray.ndarray import NDArray

    names = sorted(bindings)
    cast_dt = jnp.dtype(cast) if cast is not None else None

    def _cast(a):
        if cast_dt is not None and jnp.issubdtype(a.dtype,
                                                  jnp.floating):
            return a.astype(cast_dt)
        return a

    vals = []
    for n in names:
        v = bindings[n]
        a = _cast(v._data if isinstance(v, NDArray)
                  else jnp.asarray(np.asarray(v)))
        sh = NamedSharding(
            mesh, layout.spec_for(n, shape=a.shape, mesh=mesh))
        vals.append(jax.device_put(a, sh))
    vals = tuple(vals)

    def fwd(param_vals, inputs):
        b = {n: NDArray(v) for n, v in zip(names, param_vals)}
        for k, v in inputs.items():
            b[k] = NDArray(_cast(jnp.asarray(v)))
        out = symbol.eval_dict(b)
        outs = out if isinstance(out, (list, tuple)) else [out]
        res = []
        for o in outs:
            a = o._data
            if cast_dt is not None and \
                    jnp.issubdtype(a.dtype, jnp.floating):
                a = a.astype(jnp.float32)
            res.append(a)
        return tuple(res)

    jitted = jax.jit(fwd, out_shardings=NamedSharding(mesh, P()))
    return jitted, vals


class ShardedVariantSet:
    """One mesh-slice lane's compiled forwards — duck-types
    :class:`~mxnet_tpu.serving.variants.VariantSet` (``run`` /
    ``warmup`` / the attributes the gateway's Replica reads), so the
    scheduler, health-probe, drain and scale machinery all work on a
    sliced lane unchanged.

    ``devices`` is the slice (``tp`` distinct devices); ``layout``
    defaults to :meth:`SpecLayout.default` (the process table, env-
    overridable) with this set's ``tp_axis``. Variants: ``fp32`` and
    ``bf16`` (offline-cast params, fp32 replies). ``int8`` is not
    offered on slices — the quantized graph's per-op ranges do not
    yet carry a sharded story, and refusing beats mis-serving."""

    def __init__(self, symbol, arg_params, aux_params, input_name,
                 feature_shape, devices, variants=("fp32",),
                 layout=None, input_dtype="float32", tp_axis="tp"):
        devices = tuple(devices)
        if len(devices) < 2:
            raise MXNetError(
                f"serving: a sharded lane needs a slice of >= 2 "
                f"devices, got {len(devices)} (use VariantSet for "
                "single-device lanes)")
        if len(set(str(d) for d in devices)) != len(devices):
            raise MXNetError(
                "serving: a mesh slice cannot repeat a device")
        self.input_name = input_name
        self.feature_shape = tuple(int(s) for s in feature_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.device = devices          # what stats()/logs display
        self.devices = devices
        self.tp = len(devices)
        self.tp_axis = tp_axis
        self.variants = tuple(variants)
        self.num_outputs = len(symbol.list_outputs())
        self.int8_lowering = None      # the VariantSet contract slot
        self.layout = layout if layout is not None \
            else SpecLayout.default()
        self.mesh = create_mesh({tp_axis: len(devices)},
                                devices=list(devices))
        self._fns = {}
        bindings = dict(arg_params)
        bindings.update(aux_params)
        self._binding_names = tuple(sorted(bindings))
        for v in self.variants:
            if v not in SHARDED_VARIANTS:
                raise MXNetError(
                    f"serving: sharded lanes serve {SHARDED_VARIANTS}"
                    f", not {v!r} (int8 has no sharded lowering yet)")
            self._fns[v] = compile_symbol_forward_sharded(
                symbol, bindings, self.mesh, self.layout,
                cast="bfloat16" if v == "bf16" else None)
        self._maybe_report(bindings)

    def _maybe_report(self, bindings):
        """MXTPU_LAYOUT_REPORT: drop this lane's per-parameter
        placement report (atomic write) for audit — the serving twin
        of the dry-run artifact."""
        path = get_env("MXTPU_LAYOUT_REPORT", "", str)
        if not path:
            return
        import json

        from ..checkpoint import atomic_write
        doc = self.placement_report()
        with atomic_write(path, mode="w", manifest=False) as f:
            f.write(json.dumps(doc, indent=1) + "\n")

    def placement_report(self):
        """Per-parameter placement of this slice (layout-plane report
        shape): every binding's role, spec, and per-device bytes —
        pvals were committed in sorted-name order by the compiler."""
        from ..parallel.layout import dryrun_report
        _, pvals = self._fns[self.variants[0]]
        tree = dict(zip(self._binding_names, pvals))
        return dryrun_report(
            self.layout, tree, self.mesh,
            extra={"kind": "serving_slice", "tp": self.tp})

    # -- dispatch (the VariantSet contract) ----------------------------------
    def run(self, variant, batch):
        """Execute one padded batch as ONE SPMD program over the
        slice; numpy in, list-of-numpy out (the ``np.asarray`` is the
        reply's host transfer — outputs are replicated, so the gather
        is a local read)."""
        fn, pvals = self._fns[variant]
        outs = fn(pvals, {self.input_name: np.ascontiguousarray(batch)})
        return [np.asarray(o) for o in outs]

    def warmup(self, buckets):
        """AOT-compile every (variant, bucket) SPMD executable —
        steady-state sharded serving never retraces."""
        n = 0
        for variant in self.variants:
            for b in buckets:
                zeros = np.zeros((b,) + self.feature_shape,
                                 self.input_dtype)
                self.run(variant, zeros)
                n += 1
        return n
