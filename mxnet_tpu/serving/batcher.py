"""Continuous batching: per-model request queues + shape-bucket
coalescing.

The scheduler loop (one per replica, serving/gateway.py) calls
:meth:`ModelQueue.take_batch`, which blocks for the first pending
request and then coalesces same-variant requests into one batch of at
most ``max_rows`` rows — waiting at most until the FIRST request's
``submit + max_wait`` before dispatching partial. That knob is the
latency/throughput dial the ISSUE names: bs=1 latency is *bounded* by
``max_wait`` + one execution, never sacrificed to batch filling.

A batch never mixes variants (one XLA executable serves one dtype);
with several variants pending, the one whose head request is oldest
goes first, so no variant starves.
"""
from __future__ import annotations

import threading
from collections import deque

import numpy as np

from ..base import MXNetError
from ..tracing import clock


class ServingError(MXNetError):
    """Serving-layer failure (bad input, closed gateway, timeout)."""


class RejectedError(ServingError):
    """Fast-reject at admission (the 429 analogue): the request never
    entered the queue. ``reason`` is one of ``queue_full`` / ``slo`` /
    ``no_replica`` / ``closed``."""

    def __init__(self, reason, msg):
        super().__init__(msg)
        self.reason = reason


class Request:
    """One in-flight inference request + its reply future.

    ``data`` is a numpy array of shape ``(rows,) + feature_shape``;
    the batcher stacks requests along axis 0 and splits the outputs
    back. Timestamps (monotonic ns, tracing/clock epoch) accumulate as
    the request moves through the pipeline — the gateway records the
    request → queue → batch → execute → reply span chain from them at
    reply time.
    """

    __slots__ = ("model", "variant", "data", "rows", "trace_ctx",
                 "submit_ns", "dequeue_ns", "exec_start_ns",
                 "exec_end_ns", "attempts", "hold_ns", "requeue_ns",
                 "_event", "_result", "_error")

    def __init__(self, model, variant, data, trace_ctx):
        self.model = model
        self.variant = variant
        self.data = data
        self.rows = int(data.shape[0])
        self.trace_ctx = trace_ctx
        self.submit_ns = clock.now_ns()
        self.dequeue_ns = 0
        self.exec_start_ns = 0
        self.exec_end_ns = 0
        self.attempts = 0
        # tail-attribution decision events (profiling/tailpath.py):
        # time this request's batch spent in the coalescing hold
        # window, and time lost to failed-replica requeues
        self.hold_ns = 0
        self.requeue_ns = 0
        self._event = threading.Event()
        self._result = None
        self._error = None

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """Block for the reply: list of numpy outputs, each of shape
        ``(rows,) + output_feature``. Raises the serving-side error if
        the request failed."""
        if not self._event.wait(timeout):
            raise ServingError(
                f"serving: request on {self.model!r} timed out after "
                f"{timeout}s (still queued or executing)")
        if self._error is not None:
            raise self._error
        return self._result

    def _set_result(self, outs):
        self._result = outs
        self._event.set()

    def _set_error(self, err):
        self._error = err
        self._event.set()


class ModelQueue:
    """Pending requests for one model, segregated by variant.

    Thread-safe: producers are client threads (``Gateway.submit``),
    consumers are the replica scheduler threads. ``requeue`` puts a
    failed replica's batch back at the FRONT so surviving replicas
    redistribute it in arrival order.
    """

    def __init__(self, max_rows, max_wait_s):
        self.max_rows = int(max_rows)
        self.max_wait_s = float(max_wait_s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._by_variant = {}
        self._rows = 0
        self.closed = False

    def depth(self):
        with self._lock:
            return sum(len(d) for d in self._by_variant.values())

    def pending_rows(self):
        with self._lock:
            return self._rows

    def put(self, req):
        with self._cond:
            if self.closed:
                raise RejectedError(
                    "closed", f"serving: model {req.model!r} is closed")
            self._by_variant.setdefault(req.variant, deque()).append(req)
            self._rows += req.rows
            # notify_all, not notify: the single wakeup could land on a
            # scheduler holding a DIFFERENT variant's partial batch
            # (which scoops nothing and re-waits) while an idle replica
            # sleeps — breaking the max_wait latency bound
            self._cond.notify_all()

    def requeue(self, reqs):
        """Failed-replica redistribution: back at the front, original
        order preserved."""
        now = clock.now_ns()
        with self._cond:
            for req in reversed(reqs):
                # the failed attempt's dequeue->now is lost wall the
                # tail plane bills to `requeue`; the retry re-stamps
                # dequeue_ns when its batch actually runs
                if req.dequeue_ns:
                    req.requeue_ns += max(now - req.dequeue_ns, 0)
                    req.dequeue_ns = 0
                self._by_variant.setdefault(
                    req.variant, deque()).appendleft(req)
                self._rows += req.rows
            self._cond.notify_all()

    def close(self):
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    def drain(self):
        """Remove and return every pending request (gateway shutdown:
        the caller fails them instead of leaving clients hanging)."""
        with self._cond:
            out = []
            for d in self._by_variant.values():
                out.extend(d)
                d.clear()
            self._rows = 0
            return out

    def _scoop(self, dq, batch, rows):
        """Move as many head requests as still fit into ``batch``
        (called under the lock)."""
        while dq and rows + dq[0].rows <= self.max_rows:
            r = dq.popleft()
            batch.append(r)
            rows += r.rows
            self._rows -= r.rows
        return rows

    def take_batch(self):
        """Block until work arrives, then coalesce one same-variant
        batch. Returns ``(variant, [requests])`` or ``None`` when the
        queue closed empty."""
        with self._cond:
            while True:
                pending = [(v, d) for v, d in self._by_variant.items()
                           if d]
                if pending:
                    break
                if self.closed:
                    return None
                self._cond.wait()
            # oldest head request goes first: no variant starves
            variant, dq = min(pending,
                              key=lambda vd: vd[1][0].submit_ns)
            first = dq.popleft()
            self._rows -= first.rows
            batch = [first]
            rows = self._scoop(dq, batch, first.rows)
            deadline_ns = first.submit_ns + int(self.max_wait_s * 1e9)
            hold_ns = 0
            while rows < self.max_rows and not self.closed:
                now = clock.now_ns()
                remaining = (deadline_ns - now) / 1e9
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                hold_ns += clock.now_ns() - now
                rows = self._scoop(dq, batch, rows)
            # batch-formation hold: wall spent fishing for batch-mates
            # after the batch could have dispatched — stamped on every
            # member so the tail plane can split its queue wait into
            # backlog vs hold (clipped per request at join time)
            for r in batch:
                r.hold_ns = hold_ns
            return variant, batch


def pad_batch(reqs, bucket, feature_shape, dtype):
    """Stack request rows into one ``(bucket,) + feature_shape`` array,
    zero-padding the tail. Returns (padded, rows)."""
    rows = sum(r.rows for r in reqs)
    out = np.zeros((bucket,) + tuple(feature_shape), dtype)
    off = 0
    for r in reqs:
        out[off:off + r.rows] = r.data
        off += r.rows
    return out, rows
