"""Runtime kernel compilation (ref: python/mxnet/rtc.py — CudaModule
compiles CUDA C source at runtime).

The TPU analogue of a runtime-compiled kernel is a Pallas kernel
(`mxnet_tpu.ops.pallas_kernels`) or a jitted JAX function — both
compile at call time through XLA, which is the entire execution model
here rather than an escape hatch. CUDA source compilation is
meaningless on this backend, so the reference API surface raises a
clear error pointing at the native alternatives."""
from __future__ import annotations

from .base import MXNetError

_MSG = ("CudaModule is not supported on the TPU backend: runtime "
        "kernels are Pallas kernels or jitted JAX functions "
        "(see mxnet_tpu.ops.pallas_kernels), which XLA compiles at "
        "call time")


class CudaModule:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)


class CudaKernel:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)
