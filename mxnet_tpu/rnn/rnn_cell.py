"""Symbolic RNN cells (ref: python/mxnet/rnn/rnn_cell.py).

Cells compose symbols one step at a time; ``unroll`` lays T steps into
the graph. Under this framework the unrolled graph compiles into a
single XLA program at bind — the fused alternative (`FusedRNNCell`,
wrapping the `RNN` op's lax.scan lowering) produces the same numbers
with one op. Used by BucketingModule language models exactly as in the
reference's example/rnn.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import symbol as sym


class BaseRNNCell:
    """Abstract cell (ref: rnn_cell.py BaseRNNCell)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._counter = 0
        self._init_counter = 0

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def state_info(self):
        raise NotImplementedError

    def begin_state(self, func=None, **kwargs):
        """Initial state symbols (ref: rnn_cell.py begin_state)."""
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = f"{self._prefix}begin_state_{self._init_counter}"
            if func is None:
                states.append(sym.var(name, **kwargs))
            else:
                states.append(func(name=name, **info, **kwargs))
        return states

    def reset(self):
        self._counter = 0
        self._init_counter = 0

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell `length` steps (ref: rnn_cell.py unroll).

        inputs: one (N, T, C) symbol ("NTC"), a (T, N, C) symbol
        ("TNC"), or a list of T (N, C) symbols. Returns
        (outputs, final_states) — outputs merged back to the input
        layout when merge_outputs is not False.
        """
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            if len(seq) != length:
                raise MXNetError(
                    f"unroll: expected {length} step inputs, got {len(seq)}")
        else:
            seq = sym.SliceChannel(inputs, num_outputs=length, axis=axis,
                                   squeeze_axis=True)
            seq = [seq[i] for i in range(length)]
        states = begin_state if begin_state is not None \
            else self.begin_state()
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs is False:
            return outputs, states
        # stack along the time axis, preserving layout
        expanded = [sym.expand_dims(o, axis=axis) for o in outputs]
        merged = sym.Concat(*expanded, dim=axis)
        return merged, states


class RNNCell(BaseRNNCell):
    """Vanilla tanh/relu cell (ref: rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_"):
        super().__init__(prefix)
        self._num_hidden = num_hidden
        self._activation = activation
        p = self._prefix
        self._iW = sym.var(p + "i2h_weight")
        self._iB = sym.var(p + "i2h_bias")
        self._hW = sym.var(p + "h2h_weight")
        self._hB = sym.var(p + "h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden)}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=self._num_hidden,
                                 name=name + "i2h")
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=self._num_hidden,
                                 name=name + "h2h")
        out = sym.Activation(i2h + h2h, act_type=self._activation,
                             name=name + "out")
        return out, [out]


class LSTMCell(BaseRNNCell):
    """LSTM cell (ref: rnn_cell.py LSTMCell; Hochreiter 1997)."""

    def __init__(self, num_hidden, prefix="lstm_", forget_bias=1.0):
        super().__init__(prefix)
        self._num_hidden = num_hidden
        p = self._prefix
        self._iW = sym.var(p + "i2h_weight")
        # forget_bias is realized through the bias *initializer* (ref:
        # rnn_cell.py LSTMCell uses init.LSTMBias so trained weights
        # absorb it) — NOT an in-graph addition, which would double-apply
        # it when loading reference-format checkpoints whose biases
        # already encode the +forget_bias
        from ..initializer import LSTMBias
        self._iB = sym.var(p + "i2h_bias",
                           init=LSTMBias(forget_bias=forget_bias))
        self._hW = sym.var(p + "h2h_weight")
        self._hB = sym.var(p + "h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden)},
                {"shape": (0, self._num_hidden)}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        H = self._num_hidden
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=4 * H, name=name + "i2h")
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=4 * H, name=name + "h2h")
        gates = i2h + h2h
        split = sym.SliceChannel(gates, num_outputs=4, axis=1,
                                 name=name + "slice")
        i = sym.Activation(split[0], act_type="sigmoid")
        f = sym.Activation(split[1], act_type="sigmoid")
        g = sym.Activation(split[2], act_type="tanh")
        o = sym.Activation(split[3], act_type="sigmoid")
        c = f * states[1] + i * g
        h = o * sym.Activation(c, act_type="tanh")
        return h, [h, c]


class GRUCell(BaseRNNCell):
    """GRU cell (ref: rnn_cell.py GRUCell; Cho 2014)."""

    def __init__(self, num_hidden, prefix="gru_"):
        super().__init__(prefix)
        self._num_hidden = num_hidden
        p = self._prefix
        self._iW = sym.var(p + "i2h_weight")
        self._iB = sym.var(p + "i2h_bias")
        self._hW = sym.var(p + "h2h_weight")
        self._hB = sym.var(p + "h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden)}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        H = self._num_hidden
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=3 * H, name=name + "i2h")
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=3 * H, name=name + "h2h")
        i_split = sym.SliceChannel(i2h, num_outputs=3, axis=1)
        h_split = sym.SliceChannel(h2h, num_outputs=3, axis=1)
        i_r, i_z, i_n = (i_split[k] for k in range(3))
        h_r, h_z, h_n = (h_split[k] for k in range(3))
        r = sym.Activation(i_r + h_r, act_type="sigmoid")
        z = sym.Activation(i_z + h_z, act_type="sigmoid")
        n = sym.Activation(i_n + r * h_n, act_type="tanh")
        out = (1 - z) * n + z * states[0]
        return out, [out]


class FusedRNNCell(BaseRNNCell):
    """One fused RNN op for the whole sequence (ref: rnn_cell.py
    FusedRNNCell -> the RNN op, src/operator/rnn-inl.h:49)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, prefix="rnn_"):
        super().__init__(prefix)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional

    @property
    def state_info(self):
        dirs = 2 if self._bidirectional else 1
        info = [{"shape": (self._num_layers * dirs, 0, self._num_hidden)}]
        if self._mode == "lstm":
            info.append(dict(info[0]))
        return info

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if isinstance(inputs, (list, tuple)):
            inputs = sym.Concat(*[sym.expand_dims(i, axis=0)
                                  for i in inputs], dim=0)  # (T, N, C)
        elif layout == "NTC":
            inputs = sym.transpose(inputs, axes=(1, 0, 2))
        states = begin_state if begin_state is not None \
            else self.begin_state()
        params = sym.var(self._prefix + "parameters")
        kwargs = dict(state_size=self._num_hidden,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._bidirectional,
                      state_outputs=True)
        if self._mode == "lstm":
            out = sym.RNN(inputs, params, states[0],
                          state_cell=states[1], name=self._prefix + "rnn",
                          **kwargs)
        else:
            out = sym.RNN(inputs, params, states[0],
                          name=self._prefix + "rnn", **kwargs)
        outputs = out[0]
        if layout == "NTC":
            outputs = sym.transpose(outputs, axes=(1, 0, 2))
        n_state = len(self.state_info)
        states = [out[1 + k] for k in range(n_state)]
        if merge_outputs is False:
            # per-step list, as composite cells (Bidirectional) expect
            axis = layout.find("T")
            split = sym.SliceChannel(outputs, num_outputs=length,
                                     axis=axis, squeeze_axis=True)
            return [split[i] for i in range(length)], states
        return outputs, states


class SequentialRNNCell(BaseRNNCell):
    """Stack cells (ref: rnn_cell.py SequentialRNNCell)."""

    def __init__(self):
        super().__init__("")
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    def reset(self):
        super().reset()
        for c in self._cells:
            c.reset()

    @property
    def state_info(self):
        return [i for c in self._cells for i in c.state_info]

    def begin_state(self, **kwargs):
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def __call__(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[pos:pos + n])
            next_states.extend(st)
            pos += n
        return inputs, next_states


class BidirectionalCell(BaseRNNCell):
    """Run two cells over the sequence in opposite directions
    (ref: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell):
        super().__init__("")
        self._l = l_cell
        self._r = r_cell

    def reset(self):
        super().reset()
        self._l.reset()
        self._r.reset()

    @property
    def state_info(self):
        return self._l.state_info + self._r.state_info

    def begin_state(self, **kwargs):
        return self._l.begin_state(**kwargs) + \
            self._r.begin_state(**kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            s = sym.SliceChannel(inputs, num_outputs=length, axis=axis,
                                 squeeze_axis=True)
            inputs = [s[i] for i in range(length)]
        states = begin_state if begin_state is not None \
            else self.begin_state()
        nl = len(self._l.state_info)
        lo, ls = self._l.unroll(length, inputs, states[:nl],
                                layout="NTC", merge_outputs=False)
        ro, rs = self._r.unroll(length, list(reversed(inputs)),
                                states[nl:], layout="NTC",
                                merge_outputs=False)
        ro = list(reversed(ro))
        outs = [sym.Concat(l, r, dim=1) for l, r in zip(lo, ro)]
        if merge_outputs is False:
            return outs, ls + rs
        merged = sym.Concat(*[sym.expand_dims(o, axis=axis)
                              for o in outs], dim=axis)
        return merged, ls + rs

    def __call__(self, inputs, states):
        raise MXNetError(
            "BidirectionalCell cannot step one timestep at a time; "
            "call unroll (the reference raises the same)")


class DropoutCell(BaseRNNCell):
    """Dropout on the output stream (ref: rnn_cell.py DropoutCell)."""

    def __init__(self, dropout):
        super().__init__("")
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = sym.Dropout(inputs, p=self._dropout)
        return inputs, states


class ZoneoutCell(BaseRNNCell):
    """Zoneout regularization wrapper (ref: rnn_cell.py ZoneoutCell;
    Krueger 2016): randomly preserve previous states."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell._prefix + "zoneout_")
        self._base = base_cell
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_out = None

    def reset(self):
        # drop the previous unroll's output symbol, or a second unroll
        # would splice the old graph in (ref: ZoneoutCell.reset)
        super().reset()
        self._base.reset()
        self._prev_out = None

    @property
    def state_info(self):
        return self._base.state_info

    def begin_state(self, **kwargs):
        return self._base.begin_state(**kwargs)

    def __call__(self, inputs, states):
        out, next_states = self._base(inputs, states)

        def mask(p, new, old):
            m = sym.Dropout(sym.ones_like(new), p=p)
            return sym.where(m, new, old)

        prev = self._prev_out if self._prev_out is not None \
            else sym.zeros_like(out)
        if self._zo > 0:
            out = mask(self._zo, out, prev)
        self._prev_out = out
        if self._zs > 0:
            next_states = [mask(self._zs, n, o)
                           for n, o in zip(next_states, states)]
        return out, next_states


class ResidualCell(BaseRNNCell):
    """output = cell(x) + x (ref: rnn_cell.py ResidualCell)."""

    def __init__(self, base_cell):
        super().__init__(base_cell._prefix + "residual_")
        self._base = base_cell

    def reset(self):
        super().reset()
        self._base.reset()

    @property
    def state_info(self):
        return self._base.state_info

    def begin_state(self, **kwargs):
        return self._base.begin_state(**kwargs)

    def __call__(self, inputs, states):
        out, states = self._base(inputs, states)
        return out + inputs, states
