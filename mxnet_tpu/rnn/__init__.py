"""mx.rnn — symbolic RNN cells for explicit unrolling
(ref: python/mxnet/rnn/__init__.py)."""
from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ZoneoutCell, ResidualCell)
