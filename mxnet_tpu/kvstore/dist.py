"""Multi-process KVStore worker/server glue over the native transport.

Role assignment follows the reference's launcher contract (ref:
tools/launch.py + dmlc-core tracker env): ``DMLC_ROLE`` is ``worker`` /
``server`` / ``scheduler``, the server address comes from
``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``, worker count from
``DMLC_NUM_WORKER``. The data plane is _native/comm.cc (the ps-lite
equivalent): rank assignment at connect, BSP merge rounds, barriers,
and an optional server-side optimizer shipped as a pickled blob
(ref: python/mxnet/kvstore.py:450-495 set_optimizer).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import time

import numpy as np

from ..base import MXNetError
from .. import _native

CMD_SYNC_MODE = 1
CMD_STOP = 2
CMD_SERVER_PROFILER = 3
CMD_SET_OPTIMIZER = 4


def role():
    return os.environ.get("DMLC_ROLE", "worker")


def server_address():
    uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    return uri, port


def num_workers_env():
    return int(os.environ.get("DMLC_NUM_WORKER", "1"))


class WorkerConnection:
    """One worker's connection to the parameter server."""

    def __init__(self, host=None, port=None, timeout=30.0):
        self._lib = _native.load_comm()
        if host is None:
            host, port = server_address()
        deadline = time.monotonic() + timeout
        handle = None
        while time.monotonic() < deadline:
            handle = self._lib.mxtpu_client_connect(
                host.encode(), int(port))
            if handle:
                break
            time.sleep(0.1)
        if not handle:
            raise MXNetError(
                f"could not reach kvstore server at {host}:{port}")
        self._h = ctypes.c_void_p(handle)
        self.rank = self._lib.mxtpu_client_rank(self._h)
        self.num_workers = self._lib.mxtpu_client_num_workers(self._h)

    def _fptr(self, arr):
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    def init(self, key, value):
        arr = np.ascontiguousarray(value, dtype=np.float32)
        rc = self._lib.mxtpu_client_init(self._h, key, self._fptr(arr),
                                         arr.size)
        if rc != 0:
            raise MXNetError(f"dist init failed for key {key} (rc={rc})")

    def push(self, key, value):
        arr = np.ascontiguousarray(value, dtype=np.float32)
        rc = self._lib.mxtpu_client_push(self._h, key, self._fptr(arr),
                                         arr.size)
        if rc != 0:
            raise MXNetError(f"dist push failed for key {key} (rc={rc})")

    def push_compressed(self, key, payload):
        rc = self._lib.mxtpu_client_push_2bit(self._h, key, payload,
                                              len(payload))
        if rc != 0:
            raise MXNetError(
                f"dist compressed push failed for key {key} (rc={rc})")

    def pull(self, key, shape):
        n = int(np.prod(shape)) if shape else 1
        out = np.empty(n, dtype=np.float32)
        got = self._lib.mxtpu_client_pull(self._h, key, self._fptr(out), n)
        if got < 0:
            raise MXNetError(f"dist pull failed for key {key} (rc={got})")
        if got != n:
            raise MXNetError(
                f"dist pull size mismatch for key {key}: got {got}, "
                f"want {n} (was the key initialized?)")
        return out.reshape(shape)

    def barrier(self):
        rc = self._lib.mxtpu_client_barrier(self._h)
        if rc != 0:
            raise MXNetError(f"dist barrier failed (rc={rc})")

    def command(self, cmd, body=b""):
        rc = self._lib.mxtpu_client_command(self._h, cmd, body, len(body))
        if rc != 0:
            raise MXNetError(f"dist command {cmd} failed (rc={rc})")

    def set_sync_mode(self, sync):
        self.command(CMD_SYNC_MODE, b"\x01" if sync else b"\x00")

    def send_optimizer(self, optimizer):
        self.command(CMD_SET_OPTIMIZER, pickle.dumps(optimizer))

    def stop_server(self):
        self.command(CMD_STOP)

    def close(self):
        if self._h:
            self._lib.mxtpu_client_close(self._h)
            self._h = None


def run_server(port=None, num_workers=None, poll_ms=200):
    """Server process main loop (ref: python/mxnet/kvstore_server.py).

    Starts the native transport, then waits for control events: a
    pickled optimizer installs a Python updater applied per merge round;
    a stop command ends the loop.
    """
    lib = _native.load_comm()
    if port is None:
        _, port = server_address()
    if num_workers is None:
        num_workers = num_workers_env()
    rc = lib.mxtpu_server_start(int(port), int(num_workers))
    if rc != 0:
        raise MXNetError(f"kvstore server failed to start (rc={rc})")

    buf = ctypes.create_string_buffer(64 << 20)
    states = {}
    while True:
        got = lib.mxtpu_server_poll(buf, len(buf), poll_ms)
        if got < 0:
            break
        if got > 0:
            optimizer = pickle.loads(buf.raw[:got])

            def updater(key, recved, stored, _opt=optimizer,
                        _states=states):
                from ..ndarray import NDArray
                import jax.numpy as jnp
                w = NDArray(jnp.asarray(stored))
                g = NDArray(jnp.asarray(recved))
                if key not in _states:
                    _states[key] = _opt.create_state(key, w)
                _opt.update(key, w, g, _states[key])
                stored[:] = np.asarray(w._data, dtype=np.float32)

            _native.set_server_updater(updater)
