"""Multi-process KVStore worker/server glue over the native transport.

Role assignment follows the reference's launcher contract (ref:
tools/launch.py + dmlc-core tracker env): ``DMLC_ROLE`` is ``worker`` /
``server`` / ``scheduler``, the server address comes from
``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``, worker count from
``DMLC_NUM_WORKER``. The data plane is _native/comm.cc (the ps-lite
equivalent): rank assignment at connect, BSP merge rounds, barriers,
and an optional server-side optimizer shipped as a pickled blob
(ref: python/mxnet/kvstore.py:450-495 set_optimizer).

Recovery layer (ref: kvstore_dist.h:118-123 dead-node detection +
ps-lite resend-on-timeout): every request carries a monotonically
increasing id, so when a request dies with the connection the worker
reconnects (reclaiming its rank), pins the failed id, and RESENDS —
idempotent at the server. Retries run under exponential backoff with
jitter until ``MXNET_KVSTORE_RECOVERY_BUDGET_MS`` is spent, then raise
one clean ``MXNetError``. With the budget unset (0) the transport keeps
its legacy fail-fast behavior. The server side snapshots its whole
state on SIGTERM and restores it on start (``MXNET_KVSTORE_SNAPSHOT_
PATH``), so a restarted server — ``tools/launch.py
--restart-policy=server`` — rejoins with state intact. Deterministic
fault injection for all of this lives in ``MXNET_KVSTORE_FAULT_PLAN``
(kvstore/fault.py).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import time

import numpy as np

from ..base import MXNetError
from .. import _native
# imported at module scope ON PURPOSE: the server updater runs as a
# ctypes callback on a C++ connection thread while the main thread may
# still be mid-import of the mxnet_tpu package (the kvstore_server
# import-time entry) — a lazy `from .. import profiler` inside the
# callback would deadlock on the package's import lock
from .. import profiler
from .. import tracing as _tracing
from ..telemetry import metrics as _tm_metrics
from . import fault as fault_mod

# server-process registry families (pulled into worker dumps via the
# metrics_snapshot directive); update_s caches its series, per-key
# update counters are cached in the updater closure
_server_met = _tm_metrics.lazy_metrics(lambda reg: {
    "updates": reg.counter(
        "mx_server_updates_total",
        "merge-round optimizer updates applied",
        labelnames=("key",)),
    "update_s": reg.histogram(
        "mx_server_update_seconds",
        "server-side optimizer update latency").labels(),
})

CMD_SYNC_MODE = 1
CMD_STOP = 2
CMD_SERVER_PROFILER = 3
# profiler directives ride the same server-side blob FIFO as pickled
# optimizers; pickles start with b"\x80", so this prefix is unambiguous
PROF_MAGIC = b"PROF\x00"
CMD_SET_OPTIMIZER = 4
# exit code of a SIGTERM'd server that snapshotted its state: tells the
# launcher "restartable death with state on disk" apart from a clean
# stop (0, never restarted) and a crash (anything else)
SERVER_RESTART_EXITCODE = 17


def role():
    return os.environ.get("DMLC_ROLE", "worker")


def server_address():
    uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    return uri, port


def num_workers_env():
    return int(os.environ.get("DMLC_NUM_WORKER", "1"))


def num_servers_env():
    return int(os.environ.get("DMLC_NUM_SERVER", "1"))


def server_ports():
    """Every server's port: root_port + server_index (the launcher's
    contract; multi-server key sharding dials them all)."""
    uri, root = server_address()
    return uri, [root + i for i in range(num_servers_env())]


def request_timeout_ms():
    return int(os.environ.get("MXNET_KVSTORE_REQUEST_TIMEOUT_MS",
                              "120000"))


def recovery_budget_ms():
    """Total wall-clock the client may spend recovering ONE request
    (reconnect + resend loop). 0 disables recovery: the legacy
    fail-fast transport."""
    return int(os.environ.get("MXNET_KVSTORE_RECOVERY_BUDGET_MS", "0"))


def recovery_backoff_ms():
    return int(os.environ.get("MXNET_KVSTORE_RECOVERY_BACKOFF_MS", "50"))


def recovery_backoff_max_ms():
    return int(os.environ.get("MXNET_KVSTORE_RECOVERY_BACKOFF_MAX_MS",
                              "2000"))


_client_faults_installed = False


def _ensure_client_faults(lib):
    """Install the worker-side rules of MXNET_KVSTORE_FAULT_PLAN into
    the native client seams, once per process."""
    global _client_faults_installed
    if _client_faults_installed:
        return
    _client_faults_installed = True
    rules = fault_mod.plan_from_env()
    if rules:
        fault_mod.install_client_rules(lib, rules)


class WorkerConnection:
    """One worker's connection to the parameter server, with transparent
    reconnect/resend recovery when a budget is armed."""

    def __init__(self, host=None, port=None, timeout=30.0):
        self._lib = _native.load_comm()
        if host is None:
            host, port = server_address()
        self._host, self._port = host, int(port)
        _ensure_client_faults(self._lib)
        self._budget_ms = recovery_budget_ms()
        self.telemetry = fault_mod.RecoveryTelemetry()
        t0 = time.monotonic()
        deadline = t0 + timeout
        handle = None
        while time.monotonic() < deadline:
            handle = self._lib.mxtpu_client_connect(
                host.encode(), self._port)
            if handle:
                break
            time.sleep(0.1)
        if not handle:
            elapsed = time.monotonic() - t0
            raise MXNetError(
                f"kvstore rendezvous with server at {host}:{self._port} "
                f"failed: no connection after {elapsed:.1f}s (deadline "
                f"{timeout:.0f}s) — server process not up, wrong "
                "DMLC_PS_ROOT_URI/PORT, or the server died during startup")
        self._h = ctypes.c_void_p(handle)
        self.rank = self._lib.mxtpu_client_rank(self._h)
        self.num_workers = self._lib.mxtpu_client_num_workers(self._h)
        # bounded requests: a dead server/worker set fails the job
        # instead of hanging it (ref: kvstore_dist.h:118-123)
        self._lib.mxtpu_client_set_timeout(self._h, request_timeout_ms())

    def _fptr(self, arr):
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    @staticmethod
    def _explain(rc):
        if rc == -1:
            return ("request timed out or connection lost — server or a "
                    "peer worker may have died")
        if rc == -3:
            return ("server rejected the request (degraded: a worker "
                    "died mid-round, or the command was refused)")
        return f"rc={rc}"

    # -- recovery core -----------------------------------------------------
    def _call(self, op, invoke):
        """Span + wire-context wrapper around :meth:`_call_impl`: every
        dist request runs inside a ``kv.<op>`` span whose (trace_id,
        span_id) is stamped into the request header (wire v2) — the
        server opens the matching ``server_recv:<op>`` child span, the
        cross-process edge tools/trace_merge.py aligns clocks with."""
        if not _tracing.enabled():
            return self._call_impl(op, invoke)
        # optional on the transport: a stub/legacy lib without the
        # wire-v2 entry point simply sends untraced requests
        set_trace = getattr(self._lib, "mxtpu_client_set_trace", None)
        with _tracing.span("kv.%s" % op, cat="comm",
                           rank=self.rank) as sp:
            def stamped(h, _invoke=invoke, _sp=sp):
                # re-stamped per attempt: a recovery resend on a fresh
                # connection must carry the same span context
                if set_trace is not None:
                    set_trace(h, _sp.trace_id, _sp.span_id)
                return _invoke(h)
            rc = self._call_impl(op, stamped)
            if rc < 0:   # pull-style calls return positive sizes
                sp.set_attr("rc", int(rc))
            return rc

    def _call_impl(self, op, invoke):
        """Run ``invoke(handle) -> rc``; on a transport failure (rc -1)
        with a recovery budget armed, reconnect with the reclaimed rank,
        pin the failed request id, and resend until the budget is spent.
        Non-transport errors (-2 size, -3 rejected) pass through — the
        server answered, so resending cannot help.

        Not safe for CONCURRENT calls on one connection: the resend id
        is derived from the connection's request counter, which assumes
        requests are serialized per connection (ShardedConnection issues
        at most one in-flight request per underlying connection)."""
        rc = invoke(self._h)
        if rc != -1 or self._budget_ms <= 0:
            return rc
        failed_id = int(self._lib.mxtpu_client_get_next_req_id(self._h)) - 1
        tel = self.telemetry
        tel.last_op = op
        tel.last_req_id = failed_id
        reconnects_before = tel.reconnects
        sched = fault_mod.BackoffSchedule(
            self._budget_ms, base_ms=recovery_backoff_ms(),
            max_ms=recovery_backoff_max_ms())
        last_err = "request timed out or connection lost"
        while True:
            wait = sched.next_wait()
            if wait is None:
                break
            time.sleep(wait)
            tel.attempts += 1
            newh = self._lib.mxtpu_client_connect_as(
                self._host.encode(), self._port, self.rank)
            if not newh:
                last_err = ("reconnect refused (server down or "
                            "restarting)")
                continue
            old, self._h = self._h, ctypes.c_void_p(newh)
            # the resend must carry the SAME id the failed request
            # consumed — that is what makes it idempotent at the server
            self._lib.mxtpu_client_set_next_req_id(self._h, failed_id)
            # clamp the resend's deadline to the REMAINING budget: the
            # budget bounds the whole recovery, and one resend hanging
            # for the full request timeout would blow through it
            self._lib.mxtpu_client_set_timeout(
                self._h, max(1, min(request_timeout_ms(),
                                    int(sched.remaining_ms()))))
            self._lib.mxtpu_client_close(old)
            tel.reconnects += 1
            rc = invoke(self._h)
            if rc != -1:
                # recovered: lift the budget clamp — the next request is
                # a normal one and may legitimately park on the server
                # (BSP straggler wait) for the full request timeout
                self._lib.mxtpu_client_set_timeout(self._h,
                                                   request_timeout_ms())
                tel.recovered += 1
                tel.backoff_wait_ms += sched.total_wait_ms
                self._note(op, failed_id, "recovered", sched,
                           tel.reconnects - reconnects_before)
                return rc
            last_err = "resent request timed out or connection lost again"
        tel.exhausted += 1
        tel.backoff_wait_ms += sched.total_wait_ms
        tel.last_error = last_err
        self._note(op, failed_id, "exhausted", sched,
                   tel.reconnects - reconnects_before, last_err)
        raise MXNetError(
            f"kvstore recovery budget exhausted for {op} (request id "
            f"{failed_id}) against {self._host}:{self._port}: "
            f"{sched.attempts} attempts, {tel.reconnects} reconnects, "
            f"{sched.elapsed_ms():.0f}ms elapsed of "
            f"{self._budget_ms}ms budget; last error: {last_err}. "
            "Raise MXNET_KVSTORE_RECOVERY_BUDGET_MS or restart the "
            "server (tools/launch.py --restart-policy=server)")

    def _note(self, op, req_id, outcome, sched, reconnects, error=""):
        """One per-incident telemetry record (values for THIS recovery,
        not cumulative — the profiler summary sums across incidents)."""
        self.telemetry.events.append((op, req_id, outcome))
        profiler.note_recovery({
            "op": op, "req_id": req_id, "outcome": outcome,
            "rank": self.rank, "attempts": sched.attempts,
            "reconnects": reconnects,
            "backoff_wait_ms": round(sched.total_wait_ms, 3),
            "elapsed_ms": round(sched.elapsed_ms(), 1),
            "budget_ms": self._budget_ms, "error": error,
        })

    # -- data plane --------------------------------------------------------
    def init(self, key, value):
        arr = np.ascontiguousarray(value, dtype=np.float32)
        rc = self._call("init", lambda h: self._lib.mxtpu_client_init(
            h, key, self._fptr(arr), arr.size))
        if rc != 0:
            raise MXNetError(f"dist init failed for key {key}: "
                             f"{self._explain(rc)}")

    def push(self, key, value):
        arr = np.ascontiguousarray(value, dtype=np.float32)
        rc = self._call("push", lambda h: self._lib.mxtpu_client_push(
            h, key, self._fptr(arr), arr.size))
        if rc != 0:
            raise MXNetError(f"dist push failed for key {key}: "
                             f"{self._explain(rc)}")

    def push_compressed(self, key, payload):
        rc = self._call(
            "push_2bit", lambda h: self._lib.mxtpu_client_push_2bit(
                h, key, payload, len(payload)))
        if rc != 0:
            raise MXNetError(f"dist compressed push failed for key "
                             f"{key}: {self._explain(rc)}")

    def pull(self, key, shape):
        n = int(np.prod(shape)) if shape else 1
        out = np.empty(n, dtype=np.float32)
        got = self._call("pull", lambda h: self._lib.mxtpu_client_pull(
            h, key, self._fptr(out), n))
        if got < 0:
            raise MXNetError(f"dist pull failed for key {key}: "
                             f"{self._explain(got)}")
        if got != n:
            raise MXNetError(
                f"dist pull size mismatch for key {key}: got {got}, "
                f"want {n} (was the key initialized?)")
        return out.reshape(shape)

    def pull_rows(self, key, row_ids, row_len, total_elems=None):
        """Row-granular sparse pull: only the requested rows cross the
        wire (ref: kvstore_dist.h:470 PullRowSparse). ``total_elems``
        is accepted for signature parity with ShardedConnection."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        out = np.empty((ids.size, int(row_len)), np.float32)
        got = self._call(
            "pull_rows", lambda h: self._lib.mxtpu_client_pull_rows(
                h, key,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ids.size, int(row_len), self._fptr(out)))
        if got < 0:
            raise MXNetError(f"dist pull_rows failed for key {key}: "
                             f"{self._explain(got)}")
        if got != out.size:
            raise MXNetError(
                f"dist pull_rows size mismatch for key {key}")
        return out

    def barrier(self):
        rc = self._call("barrier",
                        lambda h: self._lib.mxtpu_client_barrier(h))
        if rc != 0:
            raise MXNetError(f"dist barrier failed: {self._explain(rc)}")

    def command(self, cmd, body=b""):
        rc = self._call("command", lambda h: self._lib.mxtpu_client_command(
            h, cmd, body, len(body)))
        if rc != 0:
            raise MXNetError(f"dist command {cmd} failed: "
                             f"{self._explain(rc)}")

    def set_sync_mode(self, sync):
        self.command(CMD_SYNC_MODE, b"\x01" if sync else b"\x00")

    def send_optimizer(self, optimizer):
        self.command(CMD_SET_OPTIMIZER, pickle.dumps(optimizer))

    def send_profiler_command(self, directive):
        """Remote-control the SERVER process's profiler (ref:
        include/mxnet/kvstore.h:43-49 kSetConfig/kState/kPause/kDump;
        kvstore_dist_server.h:199 Controller profiler branch).
        `directive` is a dict like {"cmd": "set_state", "state": "run"}
        handled by run_server's poll loop."""
        self.command(CMD_SERVER_PROFILER,
                     PROF_MAGIC + pickle.dumps(directive))

    def trace_clock_sync(self, rounds=5):
        """Emit ``rounds`` traced no-op directives over the existing
        directive channel. Each one is a worker-side ``kv.clock_sync``
        span whose server-side ``server_recv:command`` child carries the
        SERVER clock's recv timestamp — the (send, recv, ack) triples
        tools/trace_merge.py estimates per-rank clock offsets from.
        Cheap (an empty blob the server's poll loop discards); a no-op
        when tracing is disabled."""
        if not _tracing.enabled():
            return
        body = PROF_MAGIC + pickle.dumps({"cmd": "noop"})
        for _ in range(max(int(rounds), 1)):
            self._call("clock_sync",
                       lambda h: self._lib.mxtpu_client_command(
                           h, CMD_SERVER_PROFILER, body, len(body)))

    def stop_server(self):
        self.command(CMD_STOP)

    def close(self):
        if self._h:
            self._lib.mxtpu_client_close(self._h)
            self._h = None


class ShardedConnection:
    """Worker connections to S servers with key sharding
    (ref: kvstore_dist.h:532 EncodeDefaultKey — small keys round-robin
    across servers; arrays above MXNET_KVSTORE_BIGARRAY_BOUND bytes are
    split into S contiguous slices, one per server, so the push/pull
    bandwidth of a big tensor rides every server at once).

    Derived slice keys live at 1_000_000 + key * 64 + slice; user keys
    must stay below 1e6 (the reference packs keys similarly).

    Recovery composes per shard: each WorkerConnection reconnects and
    resends against its own server independently.
    """

    _SHARD_BASE = 1_000_000

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        host, ports = server_ports()
        self._conns = [WorkerConnection(host, p) for p in ports]
        self.rank = self._conns[0].rank
        self.num_workers = self._conns[0].num_workers
        # element count, matching the reference's semantics
        # (kvstore_dist.h bigarray_bound_, default 1e6 elements)
        self._big = int(float(os.environ.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND", "1000000")))
        self._sizes = {}
        # per-server socket IO releases the GIL inside ctypes — slice
        # requests genuinely overlap across servers
        self._pool = ThreadPoolExecutor(max_workers=len(self._conns))

    @property
    def num_servers(self):
        return len(self._conns)

    @property
    def telemetry(self):
        return [c.telemetry for c in self._conns]

    def _srv(self, key):
        return self._conns[key % len(self._conns)]

    def _slices(self, key, n):
        """[(server, derived_key, start, stop)] covering [0, n)."""
        S = len(self._conns)
        if key >= self._SHARD_BASE:
            raise MXNetError(f"kvstore key {key} out of range (<1e6)")
        if n < self._big or S == 1:
            return None
        per = (n + S - 1) // S
        out = []
        for i in range(S):
            start, stop = i * per, min((i + 1) * per, n)
            if start >= stop:
                break
            out.append((self._conns[i],
                        self._SHARD_BASE + key * 64 + i, start, stop))
        return out

    def init(self, key, value):
        flat = np.ascontiguousarray(value, dtype=np.float32).ravel()
        self._sizes[key] = flat.size
        sl = self._slices(key, flat.size)
        if sl is None:
            self._srv(key).init(key, flat)
            return
        for conn, dk, start, stop in sl:
            conn.init(dk, flat[start:stop])

    def push(self, key, value):
        flat = np.ascontiguousarray(value, dtype=np.float32).ravel()
        sl = self._slices(key, flat.size)
        if sl is None:
            self._srv(key).push(key, flat)
            return
        futs = [self._pool.submit(conn.push, dk, flat[start:stop])
                for conn, dk, start, stop in sl]
        for f in futs:
            f.result()

    def push_compressed(self, key, payload):
        if self._slices(key, self._sizes.get(key, 0)) is not None:
            raise MXNetError(
                "gradient compression cannot be combined with "
                f"multi-server big-array sharding (key {key}, "
                f"{self._sizes[key]} elements >= bound {self._big}); "
                "raise MXNET_KVSTORE_BIGARRAY_BOUND or use one server")
        self._srv(key).push_compressed(key, payload)

    def pull(self, key, shape):
        n = int(np.prod(shape)) if shape else 1
        sl = self._slices(key, n)
        if sl is None:
            return self._srv(key).pull(key, shape)
        out = np.empty(n, np.float32)

        def one(conn, dk, start, stop):
            out[start:stop] = conn.pull(dk, (stop - start,))

        futs = [self._pool.submit(one, *args) for args in sl]
        for f in futs:
            f.result()
        return out.reshape(shape)

    def pull_rows(self, key, row_ids, row_len, total_elems=None):
        # decide sharding from the caller-supplied size — _sizes is
        # only populated on the rank that called init()
        n = total_elems if total_elems is not None \
            else self._sizes.get(key, 0)
        if self._slices(key, n) is not None:
            # sliced keys: rows straddle server boundaries — pull full
            # and select (row-granularity is a single-server feature)
            full = self.pull(key, (n // int(row_len), int(row_len)))
            return full[np.asarray(row_ids, np.int32)]
        return self._srv(key).pull_rows(key, row_ids, row_len)

    def barrier(self):
        self._conns[0].barrier()

    def command(self, cmd, body=b""):
        for c in self._conns:
            c.command(cmd, body)

    def set_sync_mode(self, sync):
        self.command(CMD_SYNC_MODE, b"\x01" if sync else b"\x00")

    def send_optimizer(self, optimizer):
        self.command(CMD_SET_OPTIMIZER, pickle.dumps(optimizer))

    def send_profiler_command(self, directive):
        # every shard server gets the directive, like set_optimizer
        self.command(CMD_SERVER_PROFILER,
                     PROF_MAGIC + pickle.dumps(directive))

    def trace_clock_sync(self, rounds=5):
        for c in self._conns:
            c.trace_clock_sync(rounds)

    def stop_server(self):
        self.command(CMD_STOP)

    def close(self):
        for c in self._conns:
            c.close()
        self._conns = []
        self._pool.shutdown(wait=False)


def connect_workers():
    """Factory: one server -> plain connection; several -> sharded."""
    if num_servers_env() > 1:
        return ShardedConnection()
    return WorkerConnection()


def _apply_profiler_directive(body):
    """Run a worker-sent profiler command in THIS (server) process
    (ref: src/kvstore/kvstore_dist_server.h:199 — the reference's
    server Controller handles kSetConfig/kState/kPause/kDump by calling
    its own profiler; integration-tested 3-way by
    tests/nightly/test_server_profiling.py). ``metrics_snapshot``
    extends the same channel to the telemetry registry: the server
    writes its metric snapshot to the requested path, which the worker
    side polls into its own dump (telemetry.export.pull_server_metrics
    — the 'server metrics in the worker artifact' half of
    docs/observability.md)."""
    cmd = "?"
    try:
        d = pickle.loads(body)
        cmd = d.get("cmd")
        if cmd == "set_config":
            profiler.set_config(**d.get("kwargs", {}))
        elif cmd == "set_state":
            profiler.set_state(d.get("state", "stop"))
        elif cmd == "pause":
            profiler.pause()
        elif cmd == "resume":
            profiler.resume()
        elif cmd == "dump":
            profiler.dump()
        elif cmd == "metrics_snapshot":
            from ..telemetry import export as _tm_export
            _tm_export.dump(d["path"])
        elif cmd == "trace_dump":
            # worker-requested server trace file (the tracing analogue
            # of metrics_snapshot: trace_merge wants one file per rank)
            _tracing.export.write_trace(d["path"])
        elif cmd == "noop":
            pass   # clock-sync probe: the traced request IS the payload
    except Exception as e:  # noqa: BLE001 — the worker already got its
        # ACK (the command is async by design); a malformed directive
        # must not take down the poll loop the whole job depends on
        # (the reference also logs-and-continues, kvstore.h:387)
        import sys
        print("kvstore server: profiler command %r failed: %r"
              % (cmd, e), file=sys.stderr, flush=True)
        return
    profiler.record_event("server_profiler_cmd:%s" % cmd, "kvstore",
                          profiler._now_us(), 0)


def snapshot_path():
    return os.environ.get("MXNET_KVSTORE_SNAPSHOT_PATH", "")


def _write_snapshot(lib, path, optimizer_blob):
    """Serialize the whole native server state (committed stores,
    in-flight merges, idempotency watermarks) plus the optimizer blob
    to ``path``, FREEZING the server: after this no mutation can be
    acked, so nothing the snapshot missed is ever acknowledged-then-
    lost. Returns True on success."""
    cap = max(int(lib.mxtpu_server_snapshot(None, 0, 0)), 0) + (1 << 16)
    for _ in range(5):
        buf = ctypes.create_string_buffer(cap)
        got = int(lib.mxtpu_server_snapshot(buf, cap, 1))
        if got < 0:
            return False
        if got <= cap:
            blob = {"version": 1, "native": buf.raw[:got],
                    "optimizer_blob": optimizer_blob,
                    "saved_at": time.time()}
            from .. import checkpoint as ckpt
            try:
                # atomic_write adds fsync + a CRC32 manifest entry on
                # top of the tmp+rename this always did, so a restarted
                # server detects a bit-rotted snapshot instead of
                # preloading garbage state
                with ckpt.atomic_write(path) as f:
                    pickle.dump(blob, f)
            except OSError:
                # disk full / directory gone: the caller is a SIGTERM
                # handler — it must still reach its restartable exit,
                # not die on an uncaught traceback
                return False
            return True
        cap = got + (1 << 16)  # state grew between size query and copy
    return False


def _read_snapshot(path):
    import sys

    from .. import checkpoint as ckpt
    try:
        # CRC gate: a snapshot whose bytes do not match the manifest
        # entry is never preloaded as key-store state — it is logged,
        # counted, and treated as absent (the server starts empty)
        ckpt.verify(path)
        with open(path, "rb") as f:
            snap = pickle.load(f)
        if isinstance(snap, dict) and snap.get("version") == 1:
            return snap
    except MXNetError as e:
        print("kvstore server: snapshot %s failed CRC verification — "
              "starting empty (%s)" % (path, e), file=sys.stderr,
              flush=True)
        profiler.note_checkpoint_rejected({"path": path,
                                           "reason": "snapshot_crc"})
    except (OSError, ValueError, pickle.UnpicklingError, EOFError):
        pass
    return None


def run_server(port=None, num_workers=None, poll_ms=200):
    """Server process main loop (ref: python/mxnet/kvstore_server.py).

    Starts the native transport, then waits for control events: a
    pickled optimizer installs a Python updater applied per merge round;
    a stop command ends the loop. With MXNET_KVSTORE_SNAPSHOT_PATH set,
    SIGTERM snapshots the whole server state (frozen atomically) before
    exiting, and a start finding that snapshot restores it BEFORE
    listening — the restart-with-state half of the recovery protocol.
    """
    import signal
    import sys

    lib = _native.load_comm()
    if port is None:
        _, port = server_address()
        port += int(os.environ.get("DMLC_SERVER_ID", "0"))
    if num_workers is None:
        num_workers = num_workers_env()

    if _tracing.enabled():
        # traced worker requests become server_recv:* child spans in
        # THIS process's rings (dumped via the trace_dump directive or
        # MXTPU_TRACE_FILE at exit)
        from ..tracing import wire as _tw
        _tw.install_server_sink(lib)

    rules = fault_mod.plan_from_env()
    if rules:
        fault_mod.install_server_rules(lib, rules)

    snap_file = snapshot_path()
    restored = None
    if snap_file and os.path.exists(snap_file):
        restored = _read_snapshot(snap_file)
        if restored is not None:
            native = restored.get("native", b"")
            if not native or lib.mxtpu_server_preload(
                    native, len(native)) != 0:
                print("kvstore server: snapshot %s is malformed — "
                      "starting empty" % snap_file, file=sys.stderr,
                      flush=True)
                restored = None
            else:
                print("kvstore server: restored %d-byte snapshot from %s"
                      % (len(native), snap_file), file=sys.stderr,
                      flush=True)

    states = {}
    # the optimizer blob travels in the snapshot so a restarted server
    # keeps applying updates without the workers resending set_optimizer
    current = {"optimizer_blob": None}

    def install_updater(blob):
        optimizer = pickle.loads(blob)
        current["optimizer_blob"] = blob

        update_series = {}   # per-key counter series, resolved once

        def updater(key, recved, stored, _opt=optimizer, _states=states):
            from ..ndarray import NDArray
            import jax.numpy as jnp
            t0 = time.perf_counter()
            tr = _tracing.NOOP
            if _tracing.enabled():
                # parent the update span to the worker push that
                # completed the round (thread-local set by the native
                # connection thread handling that push, comm.cc);
                # untraced pushes (ctx 0,0) record nothing
                from ..tracing import wire as _tw
                ctx = _tw.server_parent_ctx(_native.load_comm())
                if ctx[0]:
                    tr = _tracing.span_at(ctx, "server_update",
                                          cat="comm", key=key,
                                          role="server")
            with tr, profiler.timed_region("server_update:key%d" % key,
                                           "kvstore"):
                w = NDArray(jnp.asarray(stored))
                g = NDArray(jnp.asarray(recved))
                if key not in _states:
                    _states[key] = _opt.create_state(key, w)
                _opt.update(key, w, g, _states[key])
                stored[:] = np.asarray(w._data, dtype=np.float32)
            if _tm_metrics.enabled():
                m = _server_met()
                s = update_series.get(key)
                if s is None:
                    s = update_series[key] = m["updates"].labels(
                        key=str(key))
                s.inc()
                m["update_s"].observe(time.perf_counter() - t0)

        _native.set_server_updater(updater)

    if snap_file:
        # installed BEFORE the listen socket opens and BEFORE the
        # consumed snapshot is unlinked: a SIGTERM at any point either
        # finds the old file still on disk (pre-start snapshots fail
        # cleanly and exit restartable) or snapshots the live state —
        # back-to-back preemptions can never destroy the only copy
        def _snapshot_and_exit(signum, frame):
            ok = _write_snapshot(lib, snap_file,
                                 current["optimizer_blob"])
            print("kvstore server: SIGTERM — snapshot %s: %s"
                  % (snap_file, "saved" if ok else "FAILED"),
                  file=sys.stderr, flush=True)
            # frozen either way; nothing more this process can do
            os._exit(SERVER_RESTART_EXITCODE)

        signal.signal(signal.SIGTERM, _snapshot_and_exit)

    # reconnect tolerance: default the grace to the workers' recovery
    # budget (the launcher forwards the whole env); a restored server
    # always gets a floor so reconnecting workers are never declared
    # dead before they can dial back in. Both the grace and a restored
    # optimizer are STAGED before start — the native side adopts them
    # pre-accept, so no worker resend racing the restart can degrade
    # the job or complete a merge round without the optimizer.
    grace = os.environ.get("MXNET_KVSTORE_RECOVERY_GRACE_MS")
    if grace is None:
        grace = os.environ.get("MXNET_KVSTORE_RECOVERY_BUDGET_MS", "0")
    grace = int(grace)
    if restored is not None and grace <= 0:
        grace = 30000
    if grace > 0:
        lib.mxtpu_server_set_recovery_grace(grace)

    if restored is not None and restored.get("optimizer_blob"):
        # NOTE: optimizer STATE (momentum etc.) restarts empty — only
        # stateless server optimizers keep exact trajectories across a
        # restart (docs/robustness.md documents the limitation)
        install_updater(restored["optimizer_blob"])

    rc = lib.mxtpu_server_start(int(port), int(num_workers))
    if rc != 0:
        raise MXNetError(f"kvstore server failed to start (rc={rc})")

    if restored is not None:
        # consumed — only now that the restored server is serving (a
        # LATER restart must snapshot fresh state, not resurrect this)
        try:
            os.unlink(snap_file)
        except OSError:
            pass

    buf = ctypes.create_string_buffer(64 << 20)
    while True:
        got = lib.mxtpu_server_poll(buf, len(buf), poll_ms)
        if got < 0:
            break
        if got > 0:
            blob = buf.raw[:got]
            if blob.startswith(PROF_MAGIC):
                _apply_profiler_directive(blob[len(PROF_MAGIC):])
                continue
            install_updater(blob)
