"""Multi-process KVStore worker/server glue over the native transport.

Role assignment follows the reference's launcher contract (ref:
tools/launch.py + dmlc-core tracker env): ``DMLC_ROLE`` is ``worker`` /
``server`` / ``scheduler``, the server address comes from
``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``, worker count from
``DMLC_NUM_WORKER``. The data plane is _native/comm.cc (the ps-lite
equivalent): rank assignment at connect, BSP merge rounds, barriers,
and an optional server-side optimizer shipped as a pickled blob
(ref: python/mxnet/kvstore.py:450-495 set_optimizer).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import time

import numpy as np

from ..base import MXNetError
from .. import _native
# imported at module scope ON PURPOSE: the server updater runs as a
# ctypes callback on a C++ connection thread while the main thread may
# still be mid-import of the mxnet_tpu package (the kvstore_server
# import-time entry) — a lazy `from .. import profiler` inside the
# callback would deadlock on the package's import lock
from .. import profiler

CMD_SYNC_MODE = 1
CMD_STOP = 2
CMD_SERVER_PROFILER = 3
# profiler directives ride the same server-side blob FIFO as pickled
# optimizers; pickles start with b"\x80", so this prefix is unambiguous
PROF_MAGIC = b"PROF\x00"
CMD_SET_OPTIMIZER = 4


def role():
    return os.environ.get("DMLC_ROLE", "worker")


def server_address():
    uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    return uri, port


def num_workers_env():
    return int(os.environ.get("DMLC_NUM_WORKER", "1"))


def num_servers_env():
    return int(os.environ.get("DMLC_NUM_SERVER", "1"))


def server_ports():
    """Every server's port: root_port + server_index (the launcher's
    contract; multi-server key sharding dials them all)."""
    uri, root = server_address()
    return uri, [root + i for i in range(num_servers_env())]


def request_timeout_ms():
    return int(os.environ.get("MXNET_KVSTORE_REQUEST_TIMEOUT_MS",
                              "120000"))


class WorkerConnection:
    """One worker's connection to the parameter server."""

    def __init__(self, host=None, port=None, timeout=30.0):
        self._lib = _native.load_comm()
        if host is None:
            host, port = server_address()
        deadline = time.monotonic() + timeout
        handle = None
        while time.monotonic() < deadline:
            handle = self._lib.mxtpu_client_connect(
                host.encode(), int(port))
            if handle:
                break
            time.sleep(0.1)
        if not handle:
            raise MXNetError(
                f"could not reach kvstore server at {host}:{port}")
        self._h = ctypes.c_void_p(handle)
        self.rank = self._lib.mxtpu_client_rank(self._h)
        self.num_workers = self._lib.mxtpu_client_num_workers(self._h)
        # bounded requests: a dead server/worker set fails the job
        # instead of hanging it (ref: kvstore_dist.h:118-123)
        self._lib.mxtpu_client_set_timeout(self._h, request_timeout_ms())

    def _fptr(self, arr):
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    @staticmethod
    def _explain(rc):
        if rc == -1:
            return ("request timed out or connection lost — server or a "
                    "peer worker may have died")
        if rc == -3:
            return ("server rejected the request (degraded: a worker "
                    "died mid-round, or the command was refused)")
        return f"rc={rc}"

    def init(self, key, value):
        arr = np.ascontiguousarray(value, dtype=np.float32)
        rc = self._lib.mxtpu_client_init(self._h, key, self._fptr(arr),
                                         arr.size)
        if rc != 0:
            raise MXNetError(f"dist init failed for key {key}: "
                             f"{self._explain(rc)}")

    def push(self, key, value):
        arr = np.ascontiguousarray(value, dtype=np.float32)
        rc = self._lib.mxtpu_client_push(self._h, key, self._fptr(arr),
                                         arr.size)
        if rc != 0:
            raise MXNetError(f"dist push failed for key {key}: "
                             f"{self._explain(rc)}")

    def push_compressed(self, key, payload):
        rc = self._lib.mxtpu_client_push_2bit(self._h, key, payload,
                                              len(payload))
        if rc != 0:
            raise MXNetError(f"dist compressed push failed for key "
                             f"{key}: {self._explain(rc)}")

    def pull(self, key, shape):
        n = int(np.prod(shape)) if shape else 1
        out = np.empty(n, dtype=np.float32)
        got = self._lib.mxtpu_client_pull(self._h, key, self._fptr(out), n)
        if got < 0:
            raise MXNetError(f"dist pull failed for key {key}: "
                             f"{self._explain(got)}")
        if got != n:
            raise MXNetError(
                f"dist pull size mismatch for key {key}: got {got}, "
                f"want {n} (was the key initialized?)")
        return out.reshape(shape)

    def pull_rows(self, key, row_ids, row_len, total_elems=None):
        """Row-granular sparse pull: only the requested rows cross the
        wire (ref: kvstore_dist.h:470 PullRowSparse). ``total_elems``
        is accepted for signature parity with ShardedConnection."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        out = np.empty((ids.size, int(row_len)), np.float32)
        got = self._lib.mxtpu_client_pull_rows(
            self._h, key,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ids.size, int(row_len), self._fptr(out))
        if got < 0:
            raise MXNetError(f"dist pull_rows failed for key {key}: "
                             f"{self._explain(got)}")
        if got != out.size:
            raise MXNetError(
                f"dist pull_rows size mismatch for key {key}")
        return out

    def barrier(self):
        rc = self._lib.mxtpu_client_barrier(self._h)
        if rc != 0:
            raise MXNetError(f"dist barrier failed: {self._explain(rc)}")

    def command(self, cmd, body=b""):
        rc = self._lib.mxtpu_client_command(self._h, cmd, body, len(body))
        if rc != 0:
            raise MXNetError(f"dist command {cmd} failed: "
                             f"{self._explain(rc)}")

    def set_sync_mode(self, sync):
        self.command(CMD_SYNC_MODE, b"\x01" if sync else b"\x00")

    def send_optimizer(self, optimizer):
        self.command(CMD_SET_OPTIMIZER, pickle.dumps(optimizer))

    def send_profiler_command(self, directive):
        """Remote-control the SERVER process's profiler (ref:
        include/mxnet/kvstore.h:43-49 kSetConfig/kState/kPause/kDump;
        kvstore_dist_server.h:199 Controller profiler branch).
        `directive` is a dict like {"cmd": "set_state", "state": "run"}
        handled by run_server's poll loop."""
        self.command(CMD_SERVER_PROFILER,
                     PROF_MAGIC + pickle.dumps(directive))

    def stop_server(self):
        self.command(CMD_STOP)

    def close(self):
        if self._h:
            self._lib.mxtpu_client_close(self._h)
            self._h = None


class ShardedConnection:
    """Worker connections to S servers with key sharding
    (ref: kvstore_dist.h:532 EncodeDefaultKey — small keys round-robin
    across servers; arrays above MXNET_KVSTORE_BIGARRAY_BOUND bytes are
    split into S contiguous slices, one per server, so the push/pull
    bandwidth of a big tensor rides every server at once).

    Derived slice keys live at 1_000_000 + key * 64 + slice; user keys
    must stay below 1e6 (the reference packs keys similarly).
    """

    _SHARD_BASE = 1_000_000

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        host, ports = server_ports()
        self._conns = [WorkerConnection(host, p) for p in ports]
        self.rank = self._conns[0].rank
        self.num_workers = self._conns[0].num_workers
        # element count, matching the reference's semantics
        # (kvstore_dist.h bigarray_bound_, default 1e6 elements)
        self._big = int(float(os.environ.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND", "1000000")))
        self._sizes = {}
        # per-server socket IO releases the GIL inside ctypes — slice
        # requests genuinely overlap across servers
        self._pool = ThreadPoolExecutor(max_workers=len(self._conns))

    @property
    def num_servers(self):
        return len(self._conns)

    def _srv(self, key):
        return self._conns[key % len(self._conns)]

    def _slices(self, key, n):
        """[(server, derived_key, start, stop)] covering [0, n)."""
        S = len(self._conns)
        if key >= self._SHARD_BASE:
            raise MXNetError(f"kvstore key {key} out of range (<1e6)")
        if n < self._big or S == 1:
            return None
        per = (n + S - 1) // S
        out = []
        for i in range(S):
            start, stop = i * per, min((i + 1) * per, n)
            if start >= stop:
                break
            out.append((self._conns[i],
                        self._SHARD_BASE + key * 64 + i, start, stop))
        return out

    def init(self, key, value):
        flat = np.ascontiguousarray(value, dtype=np.float32).ravel()
        self._sizes[key] = flat.size
        sl = self._slices(key, flat.size)
        if sl is None:
            self._srv(key).init(key, flat)
            return
        for conn, dk, start, stop in sl:
            conn.init(dk, flat[start:stop])

    def push(self, key, value):
        flat = np.ascontiguousarray(value, dtype=np.float32).ravel()
        sl = self._slices(key, flat.size)
        if sl is None:
            self._srv(key).push(key, flat)
            return
        futs = [self._pool.submit(conn.push, dk, flat[start:stop])
                for conn, dk, start, stop in sl]
        for f in futs:
            f.result()

    def push_compressed(self, key, payload):
        if self._slices(key, self._sizes.get(key, 0)) is not None:
            raise MXNetError(
                "gradient compression cannot be combined with "
                f"multi-server big-array sharding (key {key}, "
                f"{self._sizes[key]} elements >= bound {self._big}); "
                "raise MXNET_KVSTORE_BIGARRAY_BOUND or use one server")
        self._srv(key).push_compressed(key, payload)

    def pull(self, key, shape):
        n = int(np.prod(shape)) if shape else 1
        sl = self._slices(key, n)
        if sl is None:
            return self._srv(key).pull(key, shape)
        out = np.empty(n, np.float32)

        def one(conn, dk, start, stop):
            out[start:stop] = conn.pull(dk, (stop - start,))

        futs = [self._pool.submit(one, *args) for args in sl]
        for f in futs:
            f.result()
        return out.reshape(shape)

    def pull_rows(self, key, row_ids, row_len, total_elems=None):
        # decide sharding from the caller-supplied size — _sizes is
        # only populated on the rank that called init()
        n = total_elems if total_elems is not None \
            else self._sizes.get(key, 0)
        if self._slices(key, n) is not None:
            # sliced keys: rows straddle server boundaries — pull full
            # and select (row-granularity is a single-server feature)
            full = self.pull(key, (n // int(row_len), int(row_len)))
            return full[np.asarray(row_ids, np.int32)]
        return self._srv(key).pull_rows(key, row_ids, row_len)

    def barrier(self):
        self._conns[0].barrier()

    def command(self, cmd, body=b""):
        for c in self._conns:
            c.command(cmd, body)

    def set_sync_mode(self, sync):
        self.command(CMD_SYNC_MODE, b"\x01" if sync else b"\x00")

    def send_optimizer(self, optimizer):
        self.command(CMD_SET_OPTIMIZER, pickle.dumps(optimizer))

    def send_profiler_command(self, directive):
        # every shard server gets the directive, like set_optimizer
        self.command(CMD_SERVER_PROFILER,
                     PROF_MAGIC + pickle.dumps(directive))

    def stop_server(self):
        self.command(CMD_STOP)

    def close(self):
        for c in self._conns:
            c.close()
        self._conns = []
        self._pool.shutdown(wait=False)


def connect_workers():
    """Factory: one server -> plain connection; several -> sharded."""
    if num_servers_env() > 1:
        return ShardedConnection()
    return WorkerConnection()


def _apply_profiler_directive(body):
    """Run a worker-sent profiler command in THIS (server) process
    (ref: src/kvstore/kvstore_dist_server.h:199 — the reference's
    server Controller handles kSetConfig/kState/kPause/kDump by calling
    its own profiler; integration-tested 3-way by
    tests/nightly/test_server_profiling.py)."""
    cmd = "?"
    try:
        d = pickle.loads(body)
        cmd = d.get("cmd")
        if cmd == "set_config":
            profiler.set_config(**d.get("kwargs", {}))
        elif cmd == "set_state":
            profiler.set_state(d.get("state", "stop"))
        elif cmd == "pause":
            profiler.pause()
        elif cmd == "resume":
            profiler.resume()
        elif cmd == "dump":
            profiler.dump()
    except Exception as e:  # noqa: BLE001 — the worker already got its
        # ACK (the command is async by design); a malformed directive
        # must not take down the poll loop the whole job depends on
        # (the reference also logs-and-continues, kvstore.h:387)
        import sys
        print("kvstore server: profiler command %r failed: %r"
              % (cmd, e), file=sys.stderr, flush=True)
        return
    profiler.record_event("server_profiler_cmd:%s" % cmd, "kvstore",
                          profiler._now_us(), 0)


def run_server(port=None, num_workers=None, poll_ms=200):
    """Server process main loop (ref: python/mxnet/kvstore_server.py).

    Starts the native transport, then waits for control events: a
    pickled optimizer installs a Python updater applied per merge round;
    a stop command ends the loop.
    """
    lib = _native.load_comm()
    if port is None:
        _, port = server_address()
        port += int(os.environ.get("DMLC_SERVER_ID", "0"))
    if num_workers is None:
        num_workers = num_workers_env()
    rc = lib.mxtpu_server_start(int(port), int(num_workers))
    if rc != 0:
        raise MXNetError(f"kvstore server failed to start (rc={rc})")

    buf = ctypes.create_string_buffer(64 << 20)
    states = {}
    while True:
        got = lib.mxtpu_server_poll(buf, len(buf), poll_ms)
        if got < 0:
            break
        if got > 0:
            blob = buf.raw[:got]
            if blob.startswith(PROF_MAGIC):
                _apply_profiler_directive(blob[len(PROF_MAGIC):])
                continue
            optimizer = pickle.loads(blob)

            def updater(key, recved, stored, _opt=optimizer,
                        _states=states):
                from ..ndarray import NDArray
                import jax.numpy as jnp
                with profiler.timed_region("server_update:key%d" % key,
                                           "kvstore"):
                    w = NDArray(jnp.asarray(stored))
                    g = NDArray(jnp.asarray(recved))
                    if key not in _states:
                        _states[key] = _opt.create_state(key, w)
                    _opt.update(key, w, g, _states[key])
                    stored[:] = np.asarray(w._data, dtype=np.float32)

            _native.set_server_updater(updater)
