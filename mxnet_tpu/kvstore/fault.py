"""Fault-injection plans and the recovery backoff schedule.

The deterministic-fault half of the KVStore robustness layer: a plan
string in ``MXNET_KVSTORE_FAULT_PLAN`` describes WHICH faults to
provoke at WHICH protocol points, e.g.::

    drop_conn@round=3;delay_ms=500@key=0;kill_server@round=5

Each ``;``-separated directive is ``kind[=arg]`` followed by
``@cond=val`` conditions. Kinds:

``drop_conn``
    (client seam) close the connection instead of sending the matching
    request. Without ``round=`` it fires on EVERY match — a permanent
    fault; with ``round=N`` it fires once, at the Nth matching request.
``delay_ms=<ms>``
    (client or server seam) sleep before the matching request/response.
``trunc_frame``
    (client seam) send a torn frame — full header, half the payload —
    then drop the connection.
``kill_server``
    (server seam) raise SIGTERM in the server process when a key
    completes its Nth merge round (``round=N``; pin one key with
    ``key=K``, else the first key to reach round N fires it — with
    uniform BSP pushes every key's count IS the BSP round number, so
    this is model-size independent): the graceful-death path
    (run_server's handler snapshots state and exits, tools/launch.py
    ``--restart-policy=server`` restarts it).
``die_server``
    (server seam) ``_exit(86)`` at the same per-key round point —
    abrupt death, no snapshot.
``reject_accept=<count>``
    (server accept seam) close the next ``count`` accepted connections
    before rendezvous (exercises connect retry).
``kill_worker``
    (worker checkpoint seam, Python-side) SIGTERM THIS worker when its
    global batch counter reaches ``batch=N`` (checkpoint.py
    PreemptionGuard.batch_done; the counter is restored on resume, so a
    fired kill never refires after its own recovery).
``trunc_checkpoint`` / ``corrupt_checkpoint``
    (checkpoint write seam, Python-side) truncate / flip one byte of
    the Nth atomic checkpoint write (``round=N``, default the next one)
    AFTER its CRC is recorded — the torn-write/bitrot damage the
    MANIFEST.json must reject at load.
``slow_worker=<ms>``
    (worker loop seam, Python-side) the named rank (``rank=N``)
    sleeps ``ms`` of extra compute every batch, via
    :func:`apply_straggler` called inside the step span by the
    elastic train loop / chaos driver — the deterministic straggler
    whose rank PR 5's trace_merge report must name.
``borrow_wedge``
    (lending seam, Python-side) the borrower of lent training chips
    takes the lease but never reports ready (``round=N`` = the Nth
    lend; no round = every lend) — drives the LendingScheduler's
    lease-revocation path in cluster/lending.py.
``reclaim_timeout=<ms>``
    (lending seam, Python-side) the borrower drains slowly on reclaim:
    inject ``ms`` of extra drain latency into the Nth reclaim
    (``round=N``; no round = every reclaim), bounded by the reclaim
    backoff budget.
``migrate_wedge``
    (decode-recovery seam, Python-side) the Nth KV-block migration
    attempt (``round=N``; no round = every attempt) wedges mid-copy —
    the KVMigrator raises before landing blocks, forcing the recovery
    path to fall back to deterministic replay on the surviving lane.
``replay_storm``
    (decode-recovery seam, Python-side) salvage is skipped entirely
    for the Nth recovery round (``round=N``; no round = every
    recovery): every evacuated request replays prompt + accepted
    tokens from scratch — the device-truly-gone worst case.

Conditions: ``round=N`` (Nth distinct matching request, counted PER
RANK so interleaving across workers cannot move the firing point, and
a resend of the same request never re-advances the count; for
kill/die rules: a key's Nth completed merge round), ``key=K``,
``op=<init|push|pull|pull_rows|barrier|command>``, ``rank=R`` (only
workers with DMLC_WORKER_ID == R install the rule), ``server=S``
(only server S installs it), ``batch=N`` (kill_worker only: the
worker's global batch counter value to preempt at).
A ``round=``-conditioned client rule defaults to
``op=push`` — "round" means a BSP round, and the client opens one with
its push. Unknown kinds or conditions raise ``MXNetError`` — a typo'd
plan silently injecting nothing would be worse than no plan.

The recovery half lives in :class:`BackoffSchedule` (exponential
backoff with deterministic-seedable jitter under a total budget — the
client-side retry clock, unit-testable on a fake clock) and
:class:`RecoveryTelemetry` (what happened, surfaced through
profiler.py so the bench supervisor can report WHY a run degraded).
"""
from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field

from ..base import MXNetError

# mirror of the comm.cc constants (kFault*)
KIND_CODES = {
    "drop_conn": 1,
    "delay_ms": 2,
    "trunc_frame": 3,
    "kill_server": 4,
    "reject_accept": 5,
    "die_server": 6,
}
SERVER_KINDS = ("kill_server", "die_server", "reject_accept")
# Python-side checkpoint/preemption faults (mxnet_tpu/checkpoint.py):
# they never reach the native transport seams — install_client_rules /
# install_server_rules skip them. ``kill_worker@batch=N`` raises SIGTERM
# in the worker when its GLOBAL batch counter (PreemptionGuard, restored
# on resume) hits N; ``trunc_checkpoint``/``corrupt_checkpoint`` mutate
# the Nth atomic checkpoint write (``round=N``, default next) after its
# CRC is recorded, modelling the torn-write/bitrot damage the manifest
# must reject at load.
CHECKPOINT_KINDS = ("kill_worker", "trunc_checkpoint", "corrupt_checkpoint")
# Python-side straggler injection (ROADMAP item 4): ``slow_worker=MS@
# rank=N`` makes rank N sleep MS milliseconds of extra "compute" every
# batch, consumed by :func:`apply_straggler` inside the step span (the
# elastic train loop and the chaos driver both call it) — so PR 5's
# trace_merge straggler report must NAME that exact rank by its
# non-comm work. Never reaches the native seams either.
STRAGGLER_KINDS = ("slow_worker",)
# Python-side device-lending faults (mxnet_tpu/cluster/lending.py):
# ``borrow_wedge[@round=N]`` makes the Nth lend's borrower take the
# lease but never report ready (no round= — every lend), driving the
# LendingScheduler.check_leases revocation path; ``reclaim_timeout=MS
# [@round=N]`` injects a slow borrower drain of MS milliseconds into
# the Nth reclaim, which the reclaim backoff budget must bound. Like
# the straggler kinds they never reach the native seams.
LENDING_KINDS = ("borrow_wedge", "reclaim_timeout")
# Python-side decode-recovery faults (serving/generate/migrate.py):
# ``migrate_wedge[@round=N]`` wedges the Nth KV-block migration attempt
# mid-copy (no round= — every attempt), forcing the fallback to
# deterministic replay; ``replay_storm[@round=N]`` disables salvage for
# the Nth recovery round entirely, so every evacuated generation
# replays prompt + accepted tokens — the device-truly-gone worst case.
# Never reach the native seams.
DECODE_KINDS = ("migrate_wedge", "replay_storm")
# wire op codes (comm.cc kInit..kPullRows)
OP_CODES = {
    "init": 1,
    "push": 2,
    "pull": 3,
    "barrier": 4,
    "command": 5,
    "push_2bit": 6,
    "pull_rows": 7,
}
_CONDS = ("round", "key", "op", "rank", "server", "batch")


@dataclass
class FaultRule:
    kind: str
    arg: int = 0            # delay ms / reject count
    round: int | None = None
    key: int | None = None
    op: str | None = None
    rank: int | None = None
    server: int | None = None
    batch: int | None = None  # kill_worker: global batch to die at

    @property
    def is_server_side(self) -> bool:
        return self.kind in SERVER_KINDS or (
            self.kind == "delay_ms" and self.server is not None)

    @property
    def is_checkpoint_side(self) -> bool:
        return self.kind in CHECKPOINT_KINDS

    @property
    def is_python_side(self) -> bool:
        """Rules consumed by Python seams (checkpoint writes, the
        preemption guard, the straggler sleep, the lending protocol's
        wedge/timeout seams, the decode-recovery migrate/replay
        seams) — the native installers must skip them."""
        return self.kind in CHECKPOINT_KINDS or \
            self.kind in STRAGGLER_KINDS or \
            self.kind in LENDING_KINDS or \
            self.kind in DECODE_KINDS


def parse_fault_plan(plan: str) -> list[FaultRule]:
    """Parse a ``MXNET_KVSTORE_FAULT_PLAN`` string into FaultRules.

    Raises MXNetError on unknown kinds/conditions or malformed values —
    fault plans exist to make tests deterministic, so a bad plan must
    fail loudly, never silently inject nothing.
    """
    rules = []
    for directive in filter(None, (d.strip() for d in plan.split(";"))):
        head, *conds = directive.split("@")
        kind, _, argtxt = head.partition("=")
        kind = kind.strip()
        if kind not in KIND_CODES and kind not in CHECKPOINT_KINDS \
                and kind not in STRAGGLER_KINDS \
                and kind not in LENDING_KINDS \
                and kind not in DECODE_KINDS:
            raise MXNetError(
                f"unknown fault kind {kind!r} in MXNET_KVSTORE_FAULT_PLAN "
                f"directive {directive!r} (known: "
                f"{sorted(KIND_CODES) + sorted(CHECKPOINT_KINDS) + sorted(STRAGGLER_KINDS) + sorted(LENDING_KINDS) + sorted(DECODE_KINDS)})")
        rule = FaultRule(kind=kind)
        if argtxt:
            try:
                rule.arg = int(argtxt)
            except ValueError:
                raise MXNetError(
                    f"fault {directive!r}: argument {argtxt!r} is not an "
                    "integer") from None
        elif kind == "delay_ms":
            raise MXNetError(
                f"fault {directive!r}: delay_ms needs a value, e.g. "
                "delay_ms=500")
        elif kind == "reject_accept":
            rule.arg = 1
        elif kind == "slow_worker":
            raise MXNetError(
                f"fault {directive!r}: slow_worker needs a delay in "
                "ms, e.g. slow_worker=40@rank=1")
        elif kind == "reclaim_timeout":
            raise MXNetError(
                f"fault {directive!r}: reclaim_timeout needs a delay "
                "in ms, e.g. reclaim_timeout=800@round=1")
        if kind in ("borrow_wedge",) + DECODE_KINDS and argtxt:
            raise MXNetError(
                f"fault {directive!r}: {kind} takes no value "
                "(condition it with @round=N instead)")
        for cond in conds:
            name, eq, val = cond.partition("=")
            name = name.strip()
            if name not in _CONDS or not eq:
                raise MXNetError(
                    f"unknown fault condition {cond!r} in {directive!r} "
                    f"(known: {_CONDS})")
            if name == "op":
                if val not in OP_CODES:
                    raise MXNetError(
                        f"fault {directive!r}: unknown op {val!r} "
                        f"(known: {sorted(OP_CODES)})")
                rule.op = val
            else:
                try:
                    setattr(rule, name, int(val))
                except ValueError:
                    raise MXNetError(
                        f"fault {directive!r}: condition {name}={val!r} "
                        "is not an integer") from None
        if rule.kind in ("kill_server", "die_server") and rule.round is None:
            raise MXNetError(
                f"fault {directive!r}: {rule.kind} needs round=N (the "
                "merge round to die at)")
        if rule.kind == "kill_worker" and rule.batch is None:
            raise MXNetError(
                f"fault {directive!r}: kill_worker needs batch=N (the "
                "global batch to preempt at)")
        if rule.batch is not None and rule.kind != "kill_worker":
            raise MXNetError(
                f"fault {directive!r}: batch=N only applies to "
                "kill_worker")
        if rule.is_python_side:
            # the contract is fail-loudly: a condition the Python-side
            # seams never read must not be silently dropped
            allowed = {"kill_worker": ("batch", "rank"),
                       "trunc_checkpoint": ("round", "rank"),
                       "corrupt_checkpoint": ("round", "rank"),
                       "slow_worker": ("rank",),
                       "borrow_wedge": ("round",),
                       "reclaim_timeout": ("round",),
                       "migrate_wedge": ("round",),
                       "replay_storm": ("round",)}[rule.kind]
            ignored = [c for c in _CONDS
                       if getattr(rule, c) is not None and c not in allowed]
            if ignored:
                raise MXNetError(
                    f"fault {directive!r}: condition(s) {ignored} do not "
                    f"apply to {rule.kind} (allowed: {list(allowed)})")
        if (rule.round is not None and rule.op is None
                and not rule.is_server_side and not rule.is_python_side):
            # "round" on a client rule means a BSP round, which the
            # client opens with its push
            rule.op = "push"
        rules.append(rule)
    return rules


def plan_from_env() -> list[FaultRule]:
    return parse_fault_plan(os.environ.get("MXNET_KVSTORE_FAULT_PLAN", ""))


def install_client_rules(lib, rules, worker_rank=None):
    """Program the native client seams with the worker-side rules.

    ``worker_rank`` filters ``rank=``-conditioned rules (taken from
    DMLC_WORKER_ID when None). Returns how many rules were installed.
    """
    if worker_rank is None:
        worker_rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
    n = 0
    for r in rules:
        if r.is_server_side or r.is_python_side:
            continue
        if r.rank is not None and r.rank != worker_rank:
            continue
        lib.mxtpu_fault_client_add(
            KIND_CODES[r.kind], OP_CODES.get(r.op, 0) if r.op else 0,
            r.key if r.key is not None else -1,
            r.round if r.round is not None else -1, r.arg)
        n += 1
    return n


def install_server_rules(lib, rules, server_id=None):
    """Program the native server seams (kill/die/reject/delay rules)."""
    if server_id is None:
        server_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
    n = 0
    for r in rules:
        if not r.is_server_side or r.is_python_side:
            continue
        if r.server is not None and r.server != server_id:
            continue
        lib.mxtpu_fault_server_add(
            KIND_CODES[r.kind], OP_CODES.get(r.op, 0) if r.op else 0,
            r.key if r.key is not None else -1,
            r.round if r.round is not None else -1, r.arg)
        n += 1
    return n


class BackoffSchedule:
    """Exponential backoff with jitter under a total recovery budget.

    The client-side retry clock: ``next_wait()`` returns how long to
    sleep before the next reconnect attempt (None once the budget is
    exhausted), growing ``base_ms * 2^attempt`` capped at ``max_ms``,
    jittered by ±``jitter`` fraction so N workers retrying the same
    dead server don't stampede its restart in lockstep. ``clock`` and
    ``rng`` are injectable for tests (a fake clock makes the whole
    schedule assertable without sleeping).
    """

    def __init__(self, budget_ms, base_ms=50, max_ms=2000, jitter=0.25,
                 clock=time.monotonic, rng=None):
        if budget_ms <= 0:
            raise MXNetError("BackoffSchedule needs a positive budget")
        self.budget_ms = float(budget_ms)
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.jitter = float(jitter)
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self._t0 = clock()
        self.attempts = 0
        self.total_wait_ms = 0.0

    def elapsed_ms(self):
        return (self._clock() - self._t0) * 1000.0

    def remaining_ms(self):
        return self.budget_ms - self.elapsed_ms()

    def exhausted(self):
        return self.remaining_ms() <= 0

    def next_wait(self):
        """Seconds to sleep before the next attempt, or None when the
        budget is spent. Waits never overshoot the budget: the last one
        is clipped to the remaining window."""
        remaining = self.remaining_ms()
        if remaining <= 0:
            return None
        raw = min(self.base_ms * (2.0 ** self.attempts), self.max_ms)
        jit = 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        wait_ms = min(raw * jit, remaining)
        self.attempts += 1
        self.total_wait_ms += wait_ms
        return wait_ms / 1000.0


# -- straggler seam (Python-side) -----------------------------------------
# parsed slow_worker rules cached per plan string: apply_straggler runs
# once per training batch, so it must cost a dict probe, not a re-parse
_STRAGGLER_CACHE = {}  # plan string -> {rank or None: delay_ms}


def straggler_delay_ms(worker_rank=None, plan=None):
    """Delay in ms the plan's ``slow_worker`` rules impose on this rank
    (0.0 when none match). ``worker_rank`` defaults to DMLC_WORKER_ID;
    ``plan`` defaults to MXNET_KVSTORE_FAULT_PLAN."""
    if plan is None:
        plan = os.environ.get("MXNET_KVSTORE_FAULT_PLAN", "")
    if not plan:
        return 0.0
    if worker_rank is None:
        worker_rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
    by_rank = _STRAGGLER_CACHE.get(plan)
    if by_rank is None:
        by_rank = {}
        for r in parse_fault_plan(plan):
            if r.kind == "slow_worker":
                by_rank[r.rank] = by_rank.get(r.rank, 0) + r.arg
        _STRAGGLER_CACHE[plan] = by_rank
    return float(by_rank.get(int(worker_rank),
                             by_rank.get(None, 0)))


def apply_straggler(worker_rank=None, plan=None):
    """Sleep this rank's ``slow_worker`` delay (inside the caller's
    step span, so the extra wall-clock lands as COMPUTE in the
    trace_merge per-rank breakdown — a fast peer's matching wait lands
    as comm, which is exactly how the straggler report names the slow
    rank). Returns the ms slept (0.0 = no matching rule)."""
    ms = straggler_delay_ms(worker_rank, plan)
    if ms > 0:
        time.sleep(ms / 1000.0)
    return ms


# -- device-lending seams (Python-side) -----------------------------------
# parsed borrow_wedge / reclaim_timeout rules cached per plan string,
# same discipline as the straggler cache: the lending protocol probes
# these on every lend/reclaim, so it must cost a dict lookup
_LENDING_CACHE = {}  # plan string -> {"wedge": [...], "reclaim": [...]}


def _lending_rules(plan):
    if plan is None:
        plan = os.environ.get("MXNET_KVSTORE_FAULT_PLAN", "")
    if not plan:
        return {"wedge": [], "reclaim": []}
    rules = _LENDING_CACHE.get(plan)
    if rules is None:
        rules = {"wedge": [], "reclaim": []}
        for r in parse_fault_plan(plan):
            if r.kind == "borrow_wedge":
                rules["wedge"].append(r)
            elif r.kind == "reclaim_timeout":
                rules["reclaim"].append(r)
        _LENDING_CACHE[plan] = rules
    return rules


def borrow_wedge_active(lend_round=None, plan=None):
    """Whether the plan's ``borrow_wedge`` rules wedge this lend (the
    1-based ``lend_round``). A rule without ``round=`` wedges every
    lend; with ``round=N`` only the Nth. ``plan`` defaults to
    MXNET_KVSTORE_FAULT_PLAN."""
    for r in _lending_rules(plan)["wedge"]:
        if r.round is None or r.round == lend_round:
            return True
    return False


def reclaim_delay_ms(reclaim_round=None, plan=None):
    """Injected borrower-drain delay in ms for the 1-based
    ``reclaim_round`` (0.0 when no ``reclaim_timeout`` rule matches;
    rules without ``round=`` hit every reclaim)."""
    ms = 0.0
    for r in _lending_rules(plan)["reclaim"]:
        if r.round is None or r.round == reclaim_round:
            ms += r.arg
    return ms


# -- decode-recovery seams (Python-side) ----------------------------------
# parsed migrate_wedge / replay_storm rules cached per plan string, the
# same discipline as the lending cache: the decode recovery path probes
# these on every migration attempt / recovery round
_DECODE_CACHE = {}  # plan string -> {"wedge": [...], "storm": [...]}


def _decode_rules(plan):
    if plan is None:
        plan = os.environ.get("MXNET_KVSTORE_FAULT_PLAN", "")
    if not plan:
        return {"wedge": [], "storm": []}
    rules = _DECODE_CACHE.get(plan)
    if rules is None:
        rules = {"wedge": [], "storm": []}
        for r in parse_fault_plan(plan):
            if r.kind == "migrate_wedge":
                rules["wedge"].append(r)
            elif r.kind == "replay_storm":
                rules["storm"].append(r)
        _DECODE_CACHE[plan] = rules
    return rules


def migrate_wedge_active(attempt=None, plan=None):
    """Whether the plan's ``migrate_wedge`` rules wedge this KV-block
    migration (the 1-based ``attempt``). A rule without ``round=``
    wedges every attempt; with ``round=N`` only the Nth. ``plan``
    defaults to MXNET_KVSTORE_FAULT_PLAN."""
    for r in _decode_rules(plan)["wedge"]:
        if r.round is None or r.round == attempt:
            return True
    return False


def replay_storm_active(recovery_round=None, plan=None):
    """Whether the plan's ``replay_storm`` rules disable KV salvage for
    this 1-based ``recovery_round`` (rules without ``round=`` hit every
    recovery) — the device-truly-gone case, forced."""
    for r in _decode_rules(plan)["storm"]:
        if r.round is None or r.round == recovery_round:
            return True
    return False


@dataclass
class RecoveryTelemetry:
    """What the recovery protocol did — the structured answer to "why
    did this distributed run degrade". Recorded into the profiler
    stream (category ``kvstore_recovery``) and kept on the connection
    for direct inspection."""
    attempts: int = 0            # resend attempts (incl. the final one)
    reconnects: int = 0          # successful re-rendezvous count
    backoff_wait_ms: float = 0.0
    recovered: int = 0           # requests that eventually succeeded
    exhausted: int = 0           # requests that burned the whole budget
    last_op: str = ""
    last_req_id: int = 0         # round at failure (request watermark)
    last_error: str = ""
    events: list = field(default_factory=list)  # (op, req_id, outcome)
