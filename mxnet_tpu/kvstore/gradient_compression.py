"""2-bit gradient compression with error-feedback residual.

Same algorithm as the reference (ref: src/kvstore/gradient_compression.h:
37-121 GradientCompression, quantize_2bit kernel): each element of
``grad + residual`` maps to one of three codes — +threshold if >=
threshold, -threshold if <= -threshold, else 0 — and the quantization
error is kept in ``residual`` for the next round. 16 two-bit codes pack
into one little-endian u32 word (code 1 -> +threshold, 2 -> -threshold),
the exact format `accumulate_2bit` in _native/comm.cc unpacks
server-side, so compressed pushes stay compressed on the wire.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):
        if type != "2bit":
            raise MXNetError(f"unsupported compression type {type!r}")
        if threshold <= 0:
            raise MXNetError("threshold must be positive")
        self.type = type
        self.threshold = float(threshold)
        self._residual = {}

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}

    def quantize(self, key, grad):
        """grad (np.float32 array) -> (codes_u32, new_residual applied).

        Returns the packed u32 words; mutates the per-key residual.
        """
        t = self.threshold
        res = self._residual.get(key)
        if res is None or res.shape != grad.shape:
            res = np.zeros_like(grad, dtype=np.float32)
        g = grad.astype(np.float32) + res
        codes = np.zeros(g.shape, dtype=np.uint32)
        codes[g >= t] = 1
        codes[g <= -t] = 2
        decoded = np.where(codes == 1, t,
                           np.where(codes == 2, -t, 0.0)).astype(np.float32)
        self._residual[key] = g - decoded
        return self._pack(codes.ravel()), decoded

    @staticmethod
    def _pack(codes):
        n = codes.size
        nwords = (n + 15) // 16
        padded = np.zeros(nwords * 16, dtype=np.uint32)
        padded[:n] = codes
        padded = padded.reshape(nwords, 16)
        shifts = (2 * np.arange(16, dtype=np.uint32))[None, :]
        return (padded << shifts).sum(axis=1, dtype=np.uint32)

    @staticmethod
    def unpack(words, n, threshold):
        """Inverse of _pack + decode (used by tests and local fallback)."""
        words = np.asarray(words, dtype=np.uint32)
        shifts = (2 * np.arange(16, dtype=np.uint32))[None, :]
        codes = ((words[:, None] >> shifts) & 0x3).ravel()[:n]
        return np.where(codes == 1, threshold,
                        np.where(codes == 2, -threshold, 0.0)
                        ).astype(np.float32)

    def wire_payload(self, key, grad):
        """Full wire message payload for a PUSH_2BIT: f32 threshold,
        u64 n, packed words."""
        words, _ = self.quantize(key, grad)
        header = np.zeros(12, dtype=np.uint8)
        header[0:4] = np.frombuffer(
            np.float32(self.threshold).tobytes(), dtype=np.uint8)
        header[4:12] = np.frombuffer(
            np.uint64(grad.size).tobytes(), dtype=np.uint8)
        return header.tobytes() + words.tobytes()
