"""Collective data plane for dist KVStore — XLA collectives over ICI/DCN.

This is SURVEY.md §5.8's north-star contract: dense `dist_device_sync`
does NOT bounce tensors through a parameter server — every push is an
in-step all-reduce across the multi-process device mesh, compiled by XLA
onto ICI (intra-slice) / DCN (cross-slice) exactly like the reference's
`dist_device_sync` aggregates on GPUs over NCCL instead of on the PS
(ref: src/kvstore/kvstore_dist.h comm_ device reduce; kvstore.cc:55).

Process bootstrap rides `jax.distributed`: the launcher (tools/launch.py)
exports DMLC_PS_ROOT_URI/PORT + DMLC_NUM_WORKER + DMLC_WORKER_ID, and
worker 0's jax coordination service doubles as the rendezvous — no
server processes at all (launch with `-s 0`).
"""
from __future__ import annotations

import os
import threading

import numpy as np

from ..base import MXNetError

_lock = threading.Lock()
_instance = None


class CollectiveConn:
    """Per-process singleton wrapping the jax.distributed global mesh."""

    def __init__(self):
        import jax
        from jax._src import distributed as _jdist

        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT")
        n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
        # check the distributed-runtime state WITHOUT touching the XLA
        # backend (jax.process_count() would initialize it and make a
        # late jax.distributed.initialize impossible)
        if n > 1 and _jdist.global_state.client is None:
            if not (uri and port):
                raise MXNetError(
                    "collective kvstore needs DMLC_PS_ROOT_URI/PORT (set "
                    "by tools/launch.py) or a pre-initialized "
                    "jax.distributed runtime")
            try:
                jax.distributed.initialize(
                    coordinator_address=f"{uri}:{port}",
                    num_processes=n, process_id=rank)
            except RuntimeError as e:
                raise MXNetError(
                    "cannot join the collective mesh: the XLA backend was "
                    "already initialized before the dist kvstore was "
                    "created. Import mxnet_tpu with the DMLC_* launcher "
                    "env set (tools/launch.py -s 0 does this), so the "
                    "mesh forms at import time.") from e
        self.rank = jax.process_index()
        self.num_workers = jax.process_count()
        # one representative device per process forms the reduce mesh;
        # XLA routes the collective over ICI/DCN between them. (Per-host
        # multi-device replicas are already reduced by the in-step psum
        # of the SPMD executor before a kvstore push.)
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        self._mesh_devices = np.array(
            [per_proc[p] for p in sorted(per_proc)])
        from jax.sharding import Mesh
        self._mesh = Mesh(self._mesh_devices, ("proc",))
        self._reducers = {}
        self._jax = jax

    @classmethod
    def get(cls):
        global _instance
        with _lock:
            if _instance is None:
                _instance = cls()
            return _instance

    def _reducer(self, shape, dtype):
        key = (shape, str(dtype))
        if key not in self._reducers:
            import jax
            import jax.numpy as jnp

            # the device-group plumbing is the layout plane's one
            # spelling (parallel/layout.collective_shardings): stacked
            # worker slices in, replicated reduction out — the same
            # vocabulary the train-step and serving placements read
            from ..parallel.layout import collective_shardings
            in_sh, out_sh = collective_shardings(self._mesh)
            self._reducers[key] = (
                in_sh,
                jax.jit(lambda x: jnp.sum(x, axis=0),
                        out_shardings=out_sh))
        return self._reducers[key]

    def allreduce(self, value):
        """Sum `value` across all worker processes; returns numpy.

        One global array is formed with a leading process axis and
        reduced with out_shardings=replicated — XLA lowers this to an
        all-reduce over the mesh links (the literal psum-over-ICI the
        survey prescribes). Reduction runs in the value's own dtype;
        64-bit payloads are reduced under enable_x64 (jax's default
        canonicalization would silently truncate them to 32 bits)."""
        local = np.asarray(value)
        if local.dtype.itemsize == 8:
            with self._jax.enable_x64(True):
                in_sh, reduce_fn = self._reducer(local.shape, local.dtype)
                garr = self._jax.make_array_from_process_local_data(
                    in_sh, local[None],
                    (self.num_workers,) + local.shape)
                return np.asarray(reduce_fn(garr))
        in_sh, reduce_fn = self._reducer(local.shape, local.dtype)
        garr = self._jax.make_array_from_process_local_data(
            in_sh, local[None],
            (self.num_workers,) + local.shape)
        return np.asarray(reduce_fn(garr))

    def broadcast(self, value, root=0):
        """Value from `root` replicated to every process (reference
        kvstore Init semantics: rank 0 seeds, everyone pulls)."""
        local = np.asarray(value)
        if self.rank != root:
            local = np.zeros_like(local)
        return self.allreduce(local)

    def barrier(self):
        """BSP fence: a 1-element all-reduce every process must join."""
        self.allreduce(np.zeros((1,), np.float32))
