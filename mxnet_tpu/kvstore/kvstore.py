"""KVStore — the distribution seam (ref: include/mxnet/kvstore.h:59-442,
src/kvstore/kvstore_local.h).

Same string-typed factory as the reference: 'local' / 'device' aggregate
gradients within one process; 'dist_sync' / 'dist_device_sync' map data
parallelism onto XLA collectives over the device mesh (psum inside the jitted
step — no parameter-server hop needed for dense sync DP, SURVEY.md §5.8);
'dist_async' retains apply-on-arrival semantics per push. The public API
(init/push/pull/row_sparse_pull/set_optimizer/rank/num_workers) is the stable
seam Trainer and Module depend on.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.sparse import RowSparseNDArray
from ..optimizer import Updater
from ..telemetry import metrics as _tm
from ..telemetry import step as _tm_step
from .. import tracing as _tracing

_met = _tm.lazy_metrics(lambda reg: {
    "push_bytes": reg.counter(
        "mx_kvstore_push_bytes_total",
        "payload bytes pushed, by key and worker rank",
        labelnames=("key", "rank")),
    "pull_bytes": reg.counter(
        "mx_kvstore_pull_bytes_total",
        "payload bytes pulled, by key and worker rank",
        labelnames=("key", "rank")),
    "push_s": reg.histogram(
        "mx_kvstore_push_seconds",
        "host wall-clock per push call (aggregate + transport)"
        ).labels(),   # cached series
    "pull_s": reg.histogram(
        "mx_kvstore_pull_seconds",
        "host wall-clock per pull call").labels(),
})


def _nbytes(v):
    """Payload size of a push/pull value without any device sync:
    NDArrays report via their backing array's metadata, sparse values
    via their data+indices parts, host arrays via nbytes."""
    if v is None:
        return 0
    if isinstance(v, (list, tuple)):
        return sum(_nbytes(x) for x in v)
    if isinstance(v, RowSparseNDArray):
        return _nbytes(v.data) + _nbytes(v.indices)
    d = getattr(v, "_data", None)
    if d is not None:
        try:
            return int(d.size) * int(d.dtype.itemsize)
        except (AttributeError, TypeError):
            return 0
    n = getattr(v, "nbytes", None)
    try:
        return int(n)
    except (TypeError, ValueError):
        return 0


def _jax_process_count():
    try:
        return jax.process_count()
    except Exception:  # backend not yet initialized
        return 1


class KVStore:
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._conn = None
        self._coll = None
        self._update_on_server = False
        if kv_type.startswith("dist"):
            import os
            from . import dist
            serverless = os.environ.get("DMLC_NUM_SERVER") == "0"
            if kv_type in ("dist_device_sync", "dist_sync_device") and \
                    (serverless or _jax_process_count() > 1):
                # device-sync = collective data plane: gradients are
                # all-reduced by XLA over ICI/DCN inside the mesh, no
                # parameter-server hop (SURVEY §5.8; the reference's
                # analogue reduces on GPUs instead of the PS). Launch
                # with `tools/launch.py -s 0`; with servers present
                # (-s >= 1) device_sync falls through to the PS
                # transport like plain dist_sync.
                from .collective import CollectiveConn
                self._coll = CollectiveConn.get()
            elif dist.role() == "worker" and \
                    os.environ.get("DMLC_PS_ROOT_URI"):
                self._conn = dist.connect_workers()
                sync = "async" not in kv_type
                if self._conn.rank == 0:
                    self._conn.set_sync_mode(sync)
                self._conn.barrier()  # sync-mode visible to every push
                # route profiler profile_process='server' calls here
                from .. import profiler
                profiler.set_kvstore_handle(self)

    # -- factory-reported topology ----------------------------------------
    @property
    def rank(self):
        if self._conn is not None:
            return self._conn.rank
        if self._coll is not None:
            return self._coll.rank
        # single-process SPMD: jax process index is the worker rank
        return jax.process_index()

    @property
    def num_workers(self):
        if self._conn is not None:
            return self._conn.num_workers
        if self._coll is not None:
            return self._coll.num_workers
        return jax.process_count() if self.type.startswith("dist") else 1

    # -- data plane --------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        if self._coll is not None:
            # rank 0's values seed everyone (kvstore_dist.h Init contract)
            for k, v in zip(keys, values):
                dense = v.tostype("default") \
                    if isinstance(v, RowSparseNDArray) else v
                seeded = self._coll.broadcast(dense.asnumpy(), root=0)
                self._store[k] = NDArray(
                    jnp.asarray(seeded, dtype=dense._data.dtype))
            return
        for k, v in zip(keys, values):
            self._store[k] = v.copy() if isinstance(v, NDArray) else v
            if self._conn is not None:
                # every rank assigns the str->int key index in init order
                # so the map agrees across workers and with the server
                self._key_index(k)
            if self._conn is not None and self._conn.rank == 0:
                # only rank 0 seeds the server, so every worker then
                # pulls the same initial weights (kvstore_dist.h Init
                # guards the push with get_rank() == 0)
                import numpy as np
                self._conn.init(self._key_index(k),
                                np.asarray(v.asnumpy(), dtype=np.float32))
        if self._conn is not None:
            # reference workers barrier after init so no pull can race a
            # not-yet-initialized server key (kvstore_dist.h Init)
            self._conn.barrier()

    def _rank_label(self):
        r = self.__dict__.get("_tm_rank_cache")
        if r is None:
            try:
                r = str(self.rank)
            except Exception:  # noqa: BLE001 — backend not up yet:
                return "?"     # report but do NOT cache the failure
            self._tm_rank_cache = r
        return r

    def _byte_series(self, which, k):
        """Per-(direction, key) byte-counter series, cached on the
        instance — skips the labels() resolution per push/pull."""
        cache = self.__dict__.setdefault("_tm_byte_series", {})
        s = cache.get((which, k))
        if s is None:
            rank = self._rank_label()
            s = _met()[which].labels(key=str(k), rank=rank)
            if rank == "?":
                return s   # retry the rank lookup next call
            cache[(which, k)] = s
        return s

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        # span attrs are static (no host syncs — mxlint MXL006); the
        # dist transport opens kv.push children that ride the wire
        with _tracing.span("kvstore_push", cat="comm",
                           nkeys=len(keys)):
            if not _tm.enabled():
                return self._push_impl(keys, values)
            t0 = time.perf_counter()
            # record on SUCCESS only: a raising push moved no bytes —
            # a retry loop around it must not inflate the byte/latency
            # series (failures are profiler.note_recovery's job)
            ret = self._push_impl(keys, values)
            dt = time.perf_counter() - t0
            m = _met()
            m["push_s"].observe(dt)
            _tm_step.add_comm(dt)
            for k, v in zip(keys, values):
                self._byte_series("push_bytes", k).inc(_nbytes(v))
            return ret

    def _push_impl(self, keys, values):
        for k, v in zip(keys, values):
            if isinstance(v, (list, tuple)):
                # multi-device push: aggregate (CommCPU/CommDevice Reduce)
                agg = v[0]
                for extra in v[1:]:
                    agg = agg + extra
                v = agg
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized in kvstore")
            if self._coll is not None:
                # one BSP all-reduce per push round; every worker gets the
                # identical aggregate, so a local updater stays consistent
                # everywhere (the reference's server-side update becomes
                # an SPMD-replicated update)
                if isinstance(v, RowSparseNDArray):
                    v = v.tostype("default")
                agg = NDArray(jnp.asarray(
                    self._coll.allreduce(v.asnumpy()),
                    dtype=self._store[k]._data.dtype))
                if self._updater is not None:
                    self._updater(self._key_index(k), agg, self._store[k])
                else:
                    self._store[k]._data = agg._data
                continue
            if self._conn is not None:
                import numpy as np
                if isinstance(v, RowSparseNDArray):
                    v = v.tostype("default")
                grad = np.asarray(v.asnumpy(), dtype=np.float32)
                if self._compression is not None:
                    self._conn.push_compressed(
                        self._key_index(k),
                        self._compression.wire_payload(k, grad))
                else:
                    self._conn.push(self._key_index(k), grad)
                continue
            if self._updater is not None:
                self._updater(self._key_index(k), v, self._store[k])
            else:
                # no updater: the store holds the reduced push value, which
                # pull() then broadcasts (ref: kvstore_local.h PushImpl
                # CopyFromTo(merged, &local_[key]))
                if isinstance(v, RowSparseNDArray):
                    v = v.tostype("default")
                self._store[k]._data = v._data

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        with _tracing.span("kvstore_pull", cat="comm",
                           nkeys=len(keys)):
            if not _tm.enabled():
                return self._pull_impl(keys, outs)
            t0 = time.perf_counter()
            ret = self._pull_impl(keys, outs)
            dt = time.perf_counter() - t0
            m = _met()
            m["pull_s"].observe(dt)
            _tm_step.add_comm(dt)
            for k, o in zip(keys, outs):
                self._byte_series("pull_bytes", k).inc(_nbytes(o))
            return ret

    def _pull_impl(self, keys, outs):
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized in kvstore")
            targets = o if isinstance(o, (list, tuple)) else [o]
            if self._conn is not None:
                val = self._conn.pull(self._key_index(k),
                                      targets[0].shape)
                for t in targets:
                    # the wire is fp32; keep each target's own dtype so
                    # mixed-precision params don't silently widen
                    t._data = jnp.asarray(val, dtype=t._data.dtype)
                continue
            for t in targets:
                t._data = self._store[k]._data

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o, rid in zip(keys, outs, rids):
            from ..ndarray.sparse import row_sparse_array
            if self._conn is not None:
                # row-granular wire pull: only the requested rows cross
                # the network (ref: kvstore_dist.h:470 PullRowSparse)
                import numpy as np
                stored = self._store[k]
                shape = stored.shape
                row_len = int(np.prod(shape[1:])) if len(shape) > 1 else 1
                ids = (rid.asnumpy() if isinstance(rid, NDArray)
                       else np.asarray(rid)).astype(np.int32).ravel()
                rows = self._conn.pull_rows(
                    self._key_index(k), ids, row_len,
                    total_elems=int(np.prod(shape)))
                rows = rows.reshape((ids.size,) + tuple(shape[1:]))
                result = RowSparseNDArray(
                    # wire is fp32; keep the stored dtype so
                    # mixed-precision params don't silently widen
                    NDArray(jnp.asarray(rows,
                                        dtype=stored._data.dtype)),
                    NDArray(jnp.asarray(ids)), shape)
            else:
                stored = self._store[k]
                rsp = stored if isinstance(stored, RowSparseNDArray) \
                    else row_sparse_array(stored)
                result = rsp.retain(rid)
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                if isinstance(t, RowSparseNDArray):
                    t.data = result.data
                    t.indices = result.indices
                else:
                    t._data = result.tostype("default")._data

    # -- control plane -----------------------------------------------------
    def set_optimizer(self, optimizer):
        """Single-process: run the updater locally. Dist: pickle the
        optimizer to the server, matching the reference's contract
        (python/mxnet/kvstore.py:450-495) — pushes then carry gradients
        and pulls return server-updated weights."""
        self._optimizer = optimizer
        if self._coll is not None:
            # every process applies the same update to the same aggregate
            # — state stays replicated, checkpointable locally
            self._updater = Updater(optimizer)
            self._coll.barrier()
        elif self._conn is not None:
            if self._conn.rank == 0:
                self._conn.send_optimizer(optimizer)
            self._conn.barrier()
            self._update_on_server = True
            self._updater = None
        else:
            self._updater = Updater(optimizer)

    def set_gradient_compression(self, compression_params):
        from .gradient_compression import GradientCompression
        self._compression = GradientCompression(**dict(compression_params))

    @property
    def gradient_compression(self):
        return self._compression

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._update_on_server:
            raise MXNetError(
                "optimizer state lives on the kvstore server in "
                f"{self.type} mode; checkpoint from the server process "
                "or use update_on_kvstore=False")
        if self._updater is None:
            raise MXNetError("no optimizer set")
        from ..checkpoint import atomic_write
        with atomic_write(fname) as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._update_on_server:
            raise MXNetError(
                "optimizer state lives on the kvstore server in "
                f"{self.type} mode; restore it in the server process "
                "or use update_on_kvstore=False")
        if self._updater is None:
            raise MXNetError("no optimizer set")
        from ..checkpoint import verify
        verify(fname)
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        if self._conn is not None:
            self._conn.barrier()
            return
        if self._coll is not None:
            self._coll.barrier()
            return
        from .. import engine
        engine.waitall()

    def _barrier(self):
        self.barrier()

    def send_command_to_servers(self, head, body):
        if self._conn is not None:
            body = body.encode() if isinstance(body, str) else bytes(body)
            self._conn.command(int(head), body)

    def close(self):
        """Finalize: barrier all workers, rank 0 stops the server (the
        ps-lite Finalize analogue)."""
        if self._coll is not None:
            self._coll.barrier()
        if self._conn is not None:
            try:
                self._conn.barrier()
                if self._conn.rank == 0:
                    self._conn.stop_server()
            finally:
                self._conn.close()
                self._conn = None

    def _normalize(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        if value is None:
            values = [None] * len(keys)
        elif isinstance(value, (list, tuple)) and len(keys) > 1 and \
                len(value) == len(keys):
            values = list(value)
        elif isinstance(value, (list, tuple)) and len(keys) == 1:
            values = [value]
        else:
            values = [value]
        return keys, values

    def _key_index(self, k):
        # integer keys (Trainer param indices) pass through unchanged so the
        # updater's per-index state/lr-mult bookkeeping lines up; string keys
        # get a stable per-instance mapping (str<->int dict, kvstore_local.h)
        if isinstance(k, int):
            return k
        if not hasattr(self, "_str_key_indices"):
            self._str_key_indices = {}
        if k not in self._str_key_indices:
            self._str_key_indices[k] = len(self._str_key_indices)
        return self._str_key_indices[k]


def create(name="local"):
    """Factory (ref: src/kvstore/kvstore.cc:40-77)."""
    known = ("local", "device", "local_allreduce_cpu",
             "local_allreduce_device", "nccl", "dist_sync", "dist_async",
             "dist_device_sync", "dist_sync_device", "dist")
    if name not in known:
        raise MXNetError(f"unknown KVStore type {name!r}")
    return KVStore(name)
