// Native runtime core: storage pool + dependency engine + C API shim.
//
// The reference keeps these native (SURVEY §2.1/§2.7):
//  - Storage: pooled per-device allocators behind one singleton
//    (src/storage/pooled_storage_manager.h:52 GPUPooledStorageManager,
//    src/storage/storage.cc:36). On TPU, device memory belongs to PJRT;
//    the native pool owns the HOST side: aligned, size-bucketed, reused
//    buffers feeding the data pipeline (the CPUPinned/shm analogue —
//    batch staging buffers that would otherwise be malloc'd per batch).
//  - Dependency engine: ops are closures with read/write variable sets;
//    writers to a var serialize in push order, readers run concurrently
//    (include/mxnet/engine.h:115, src/engine/threaded_engine.h:66
//    ThreadedVar). XLA schedules device work; this engine schedules the
//    HOST side of the framework (decode, prefetch, file IO) with the
//    same dependency discipline, and is the MXNET_ENGINE_TYPE seam.
//  - C API: flat extern-C ABI with thread-local error strings
//    (include/mxnet/c_api.h MXGetLastError; src/c_api/).
//
// C ABI (ctypes):
//   mxtpu_version() -> int
//   mxtpu_get_last_error() -> const char*      (thread-local)
//   -- storage --
//   mxtpu_storage_alloc(size_t) -> void*
//   mxtpu_storage_free(void*)                  (returns to pool)
//   mxtpu_storage_direct_free(void*)           (bypasses pool)
//   mxtpu_storage_release_all()
//   mxtpu_storage_stats(uint64_t out[4])       (alloc'd, pooled bytes,
//                                               hits, misses)
//   -- engine --
//   mxtpu_engine_start(int nthreads) -> int
//   mxtpu_engine_new_var() -> int64
//   mxtpu_engine_push(fn, arg, read[], nread, write[], nwrite) -> int
//        fn: int(*)(void* arg); nonzero return marks the op failed and
//        poisons its write vars (rethrown at wait, threaded_engine.cc:472)
//   mxtpu_engine_wait_for_var(int64) -> int    (0 ok, -1 poisoned)
//   mxtpu_engine_wait_all() -> int
//   mxtpu_engine_stop()
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

thread_local std::string tls_error;

// ---------------------------------------------------------------------
// storage pool (ref: pooled_storage_manager.h round-to-bucket free lists)
// ---------------------------------------------------------------------
class StoragePool {
 public:
  static StoragePool& Get() {
    static StoragePool inst;
    return inst;
  }

  void* Alloc(size_t size) {
    size_t bucket = RoundSize(size);
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = free_.find(bucket);
      if (it != free_.end() && !it->second.empty()) {
        void* p = it->second.back();
        it->second.pop_back();
        pooled_bytes_ -= bucket;
        ++hits_;
        sizes_[p] = bucket;
        return p;
      }
      ++misses_;
    }
    void* p = aligned_alloc(64, bucket);
    if (p == nullptr) {
      tls_error = "mxtpu_storage_alloc: out of host memory";
      return nullptr;
    }
    std::lock_guard<std::mutex> lk(mu_);
    alloc_bytes_ += bucket;
    sizes_[p] = bucket;
    return p;
  }

  void Free(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it == sizes_.end()) return;
    free_[it->second].push_back(p);
    pooled_bytes_ += it->second;
  }

  void DirectFree(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it == sizes_.end()) return;
    alloc_bytes_ -= it->second;
    sizes_.erase(it);
    ::free(p);
  }

  void ReleaseAll() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : free_) {
      for (void* p : kv.second) {
        alloc_bytes_ -= kv.first;
        sizes_.erase(p);
        ::free(p);
      }
    }
    free_.clear();
    pooled_bytes_ = 0;
  }

  void Stats(uint64_t out[4]) {
    std::lock_guard<std::mutex> lk(mu_);
    out[0] = alloc_bytes_;
    out[1] = pooled_bytes_;
    out[2] = hits_;
    out[3] = misses_;
  }

 private:
  static size_t RoundSize(size_t size) {
    // round small sizes to the next power of two, large ones to 4 KiB
    // pages (ref: GPUPooledStorageManager MXNET_GPU_MEM_POOL_PAGE_SIZE)
    if (size < 64) return 64;
    if (size <= (1u << 20)) {
      size_t b = 64;
      while (b < size) b <<= 1;
      return b;
    }
    return (size + 4095) & ~size_t(4095);
  }

  std::mutex mu_;
  std::unordered_map<size_t, std::vector<void*>> free_;
  std::unordered_map<void*, size_t> sizes_;
  uint64_t alloc_bytes_ = 0, pooled_bytes_ = 0, hits_ = 0, misses_ = 0;
};

// ---------------------------------------------------------------------
// dependency engine (ref: threaded_engine.h ThreadedVar/OprBlock)
// ---------------------------------------------------------------------
using OpFn = int (*)(void*);

struct Opr;

struct Var {
  // reader-writer dependency queue, the ThreadedVar discipline:
  // pending ops in push order; reads at the head run together, a write
  // runs alone after all prior reads complete.
  std::deque<std::pair<Opr*, bool>> queue;  // (op, is_write)
  int running_reads = 0;
  bool running_write = false;
  bool poisoned = false;  // a writer failed (exception propagation)
  bool pending_delete = false;  // erase once idle (async DeleteVariable)
};

struct Opr {
  OpFn fn;
  void* arg;
  std::vector<int64_t> reads, writes;
  std::atomic<int> wait{0};
};

class Engine {
 public:
  static Engine& Get() {
    static Engine inst;
    return inst;
  }

  ~Engine() { Stop(); }  // joinable threads at static destruction
                         // would std::terminate

  int Start(int nthreads) {
    std::lock_guard<std::mutex> lk(mu_);
    if (running_) return 0;
    if (nthreads <= 0) {
      const char* env = getenv("MXNET_CPU_WORKER_NTHREADS");
      nthreads = env ? atoi(env) : (int)std::thread::hardware_concurrency();
      // host tasks are IO-bound: floor at 4 threads even on small hosts
      // (the reference keeps a 4-thread CPU priority pool,
      // threaded_engine_perdevice.cc:76-90)
      if (!env && nthreads < 4) nthreads = 4;
      if (nthreads <= 0) nthreads = 1;
    }
    running_ = true;
    for (int i = 0; i < nthreads; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
    return nthreads;
  }

  void Stop() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (!running_) return;
      all_done_.wait(lk, [this] { return pending_ == 0; });
      running_ = false;
      work_cv_.notify_all();
    }
    for (auto& t : workers_) t.join();
    workers_.clear();
  }

  int64_t NewVar() {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, Var{});
    return id;
  }

  void DeleteVar(int64_t id) {
    // ref: Engine::DeleteVariable is asynchronous — the var disappears
    // after its in-flight ops drain; the caller must not push to it
    // again. Idle vars erase immediately, busy ones on last completion.
    std::lock_guard<std::mutex> lk(mu_);
    auto it = vars_.find(id);
    if (it == vars_.end()) return;
    it->second.pending_delete = true;
    MaybeErase(it);
  }

  int Push(OpFn fn, void* arg, const int64_t* reads, int nread,
           const int64_t* writes, int nwrite) {
    // the reference asserts const/mutable var sets are disjoint and
    // duplicate-free (engine.h PushAsync contract); overlap here would
    // queue the op behind its own admitted slot = permanent deadlock
    std::vector<int64_t> rv(reads, reads + nread);
    std::vector<int64_t> wv(writes, writes + nwrite);
    std::sort(rv.begin(), rv.end());
    rv.erase(std::unique(rv.begin(), rv.end()), rv.end());
    std::sort(wv.begin(), wv.end());
    if (std::adjacent_find(wv.begin(), wv.end()) != wv.end()) {
      tls_error = "mxtpu_engine_push: duplicate write var";
      return -1;
    }
    for (int64_t w : wv) {
      if (std::binary_search(rv.begin(), rv.end(), w)) {
        tls_error = "mxtpu_engine_push: var in both read and write sets";
        return -1;
      }
    }
    Opr* op = new Opr{fn, arg, std::move(rv), std::move(wv)};
    std::lock_guard<std::mutex> lk(mu_);
    if (!running_) {
      delete op;
      tls_error = "engine not started";
      return -1;
    }
    ++pending_;
    // every dependency registers in the var queues; wait counts the
    // vars that cannot be satisfied immediately
    int blocked = 0;
    for (int64_t r : op->reads) {
      Var& v = vars_[r];
      if (v.queue.empty() && !v.running_write) {
        ++v.running_reads;  // read admitted now
      } else {
        v.queue.emplace_back(op, false);
        ++blocked;
      }
    }
    for (int64_t w : op->writes) {
      Var& v = vars_[w];
      if (v.queue.empty() && !v.running_write && v.running_reads == 0) {
        v.running_write = true;  // write admitted now
      } else {
        v.queue.emplace_back(op, true);
        ++blocked;
      }
    }
    op->wait.store(blocked, std::memory_order_relaxed);
    if (blocked == 0) {
      ready_.push_back(op);
      work_cv_.notify_one();
    }
    return 0;
  }

  int WaitForVar(int64_t var) {
    std::unique_lock<std::mutex> lk(mu_);
    var_cv_.wait(lk, [&] {
      auto it = vars_.find(var);
      if (it == vars_.end()) return true;
      return it->second.queue.empty() && !it->second.running_write &&
             it->second.running_reads == 0;
    });
    auto it = vars_.find(var);
    if (it != vars_.end() && it->second.poisoned) {
      it->second.poisoned = false;  // rethrow-once, like WaitForVar
      tls_error = last_op_error_;
      return -1;
    }
    return 0;
  }

  int WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    all_done_.wait(lk, [this] { return pending_ == 0; });
    for (auto& kv : vars_) {
      if (kv.second.poisoned) {
        kv.second.poisoned = false;
        tls_error = last_op_error_;
        return -1;
      }
    }
    return 0;
  }

 private:
  void WorkerLoop() {
    for (;;) {
      Opr* op;
      {
        std::unique_lock<std::mutex> lk(mu_);
        work_cv_.wait(lk, [this] { return !ready_.empty() || !running_; });
        if (!running_ && ready_.empty()) return;
        op = ready_.front();
        ready_.pop_front();
      }
      int rc = op->fn(op->arg);
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (rc != 0) {
          last_op_error_ = "engine op failed with code " +
                           std::to_string(rc);
          for (int64_t w : op->writes) vars_[w].poisoned = true;
        }
        for (int64_t r : op->reads) CompleteRead(r);
        for (int64_t w : op->writes) CompleteWrite(w);
        --pending_;
        delete op;
        var_cv_.notify_all();
        if (pending_ == 0) all_done_.notify_all();
      }
    }
  }

  // bump a var's queue after a completed read/write (mu_ held)
  void CompleteRead(int64_t id) {
    auto it = vars_.find(id);
    if (it == vars_.end()) return;
    --it->second.running_reads;
    Advance(it->second);
    MaybeErase(it);
  }
  void CompleteWrite(int64_t id) {
    auto it = vars_.find(id);
    if (it == vars_.end()) return;
    it->second.running_write = false;
    Advance(it->second);
    MaybeErase(it);
  }
  void MaybeErase(std::unordered_map<int64_t, Var>::iterator it) {
    Var& v = it->second;
    if (v.pending_delete && v.queue.empty() && !v.running_write &&
        v.running_reads == 0)
      vars_.erase(it);
  }
  void Advance(Var& v) {
    // admit from the queue head: either one write (when idle) or a
    // maximal run of reads
    while (!v.queue.empty()) {
      auto [op, is_write] = v.queue.front();
      if (is_write) {
        if (v.running_reads > 0 || v.running_write) break;
        v.running_write = true;
      } else {
        if (v.running_write) break;
        ++v.running_reads;
      }
      v.queue.pop_front();
      if (op->wait.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        ready_.push_back(op);
        work_cv_.notify_one();
      }
      if (is_write) break;  // a write runs alone
    }
  }

  std::mutex mu_;
  std::condition_variable work_cv_, var_cv_, all_done_;
  std::deque<Opr*> ready_;
  std::unordered_map<int64_t, Var> vars_;
  std::vector<std::thread> workers_;
  std::string last_op_error_;
  int64_t next_var_ = 1;
  int pending_ = 0;
  bool running_ = false;
};

}  // namespace

extern "C" {

int mxtpu_version() { return 10000; }  // 1.0.0

const char* mxtpu_get_last_error() { return tls_error.c_str(); }

void* mxtpu_storage_alloc(size_t size) {
  return StoragePool::Get().Alloc(size);
}
void mxtpu_storage_free(void* p) { StoragePool::Get().Free(p); }
void mxtpu_storage_direct_free(void* p) { StoragePool::Get().DirectFree(p); }
void mxtpu_storage_release_all() { StoragePool::Get().ReleaseAll(); }
void mxtpu_storage_stats(uint64_t out[4]) { StoragePool::Get().Stats(out); }

int mxtpu_engine_start(int nthreads) { return Engine::Get().Start(nthreads); }
void mxtpu_engine_stop() { Engine::Get().Stop(); }
int64_t mxtpu_engine_new_var() { return Engine::Get().NewVar(); }
void mxtpu_engine_delete_var(int64_t var) { Engine::Get().DeleteVar(var); }
int mxtpu_engine_push(OpFn fn, void* arg, const int64_t* reads, int nread,
                      const int64_t* writes, int nwrite) {
  return Engine::Get().Push(fn, arg, reads, nread, writes, nwrite);
}
int mxtpu_engine_wait_for_var(int64_t var) {
  return Engine::Get().WaitForVar(var);
}
int mxtpu_engine_wait_all() { return Engine::Get().WaitAll(); }

}  // extern "C"
